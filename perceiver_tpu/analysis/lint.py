"""Source-level (AST) linter with JAX-specific rules.

The graph passes catch what made it into the lowered module; these
rules catch what never should have been written — host syncs and
Python-time effects inside traced code, numpy/jax.numpy mixing in ops
code, and enum-like config fields without config-time validation.

Rules (names are the ``check`` field of emitted violations):

``jit-host-sync``
    Inside jit-traced functions: ``.item()`` calls, ``float()``/
    ``int()``/``bool()`` applied to traced function parameters, and
    ``np.*`` calls (which force the tracer to concretize — a trace
    error at best, a silent host round-trip at worst).

``jit-python-rng-time``
    ``time.*``, ``random.*``, ``np.random.*``, ``datetime.*.now`` calls
    inside jit-traced functions: they run once at trace time and
    freeze into the compiled graph as constants.

``ops-numpy-mix``
    A module under ``perceiver_tpu/ops/`` importing both ``numpy`` and
    ``jax.numpy`` at top level. Host-side precompute belongs in
    np-only modules (see ``ops/fourier.py``); traced code in jnp-only
    modules — one module doing both is where np-on-traced-values bugs
    breed.

``impl-field-validation``
    A dataclass field named ``*_impl`` (the repo's string-enum
    convention) whose defining class has no domain validation in
    ``__post_init__``. The canonical form is
    ``if self.<field> not in <valid set>: raise`` — a positive ``in``
    test conjoined with other conditions (e.g. the dropout-support
    guards) is a feature check, not domain validation, and does not
    count. An unvalidated value fails deep inside a jit trace instead
    of at config time (ADVICE r5 on ``tasks/base.py``).

``uncached-compile``
    A raw AOT compile — ``.lower(...).compile()`` chained, or
    ``x.compile()`` where ``x`` was assigned from a ``.lower(...)``
    call — anywhere outside ``perceiver_tpu/cache/``. Every AOT
    compile is supposed to flow through the persistent executable
    cache (``perceiver_tpu.cache.aot_compile``/``compile_lowered``)
    so warm starts can deserialize instead of recompiling; a raw
    compile silently opts its call site out. Diagnostics that
    intentionally measure compilation suppress per line with a
    reason.

``silent-swallow``
    Broad exception handlers that discard the failure: a bare
    ``except:`` (it also eats ``KeyboardInterrupt``/``SystemExit``),
    or an ``except Exception``/``except BaseException`` whose body is
    only ``pass``/``...``. Silently swallowed errors are how a
    production system loses data without logging a byte
    (docs/RESILIENCE.md) — every such handler must either narrow the
    exception type, handle it visibly, or carry a reason comment on
    the ``except``/``pass`` line explaining why discarding is correct.

``serving-host-sync``
    Device synchronization inside ``serving/engine.py``: ``.item()``,
    ``.tolist()``, ``.block_until_ready()``, ``jax.device_get``, and
    numpy conversion calls (``np.asarray``/``np.array``/``np.copy``/
    ``np.ascontiguousarray``) anywhere in the engine module. The
    engine's dispatch path must stay sync-free so dispatches pipeline
    like train steps; materializing results — and timing them —
    belongs to the consumer layer (``serving/api.py``, the batcher).
    Scoped to the whole engine module on purpose: a sync in a helper
    called from dispatch stalls the pipeline exactly the same way.

``unsharded-pjit``
    A ``jax.jit``/``pjit`` call or decorator inside the SPMD code
    paths (modules under ``perceiver_tpu/parallel/`` and
    ``perceiver_tpu/training/spmd.py``) that omits explicit
    ``in_shardings`` or ``out_shardings``. Silent sharding propagation
    is how replication sneaks in: GSPMD happily materializes an
    unconstrained operand fully replicated, and nothing fails until a
    real slice runs out of HBM — declare the layout at every pjit
    boundary and let ``replication_check`` verify what lowering did
    with it. Single-device jits that truly have no layout (rare in
    these modules) suppress per line with a reason.

``metrics-conventions``
    Prometheus naming discipline at every metric registration site —
    a ``.counter("name", ...)``/``.gauge(...)``/``.histogram(...)``
    call with a string-literal name. Names must be snake_case with a
    plane prefix (``serving_``/``training_``/``fleet_``) so one fleet
    exposition can merge replica, router, and trainer series without
    collisions; counters must end ``_total`` (the exposition suffix
    convention scrapers and recording rules key on) and gauges/
    histograms must not (``_total`` on a non-counter misleads every
    rate() written against it). Misnamed metrics don't fail at
    registration — they fail months later in dashboards that filter
    on the suffix.

``router-blocking-io``
    Blocking socket I/O without a deadline inside the fleet's
    router/replica hot paths (modules under ``perceiver_tpu/fleet/``):
    a ``.recv``/``.recv_into``/``.recvfrom``/``.accept`` call whose
    receiver never gets a ``.settimeout(...)`` in the same module, or
    a ``socket.create_connection`` without a ``timeout`` argument. A
    bare blocking read turns one stalled replica into a hung router
    thread — the failover contract (retry-on-sibling under a deadline,
    docs/SERVING.md "Fleet") requires every socket operation to be
    able to time out.

``distributed-blocking-io``
    The multi-host discipline (modules under
    ``perceiver_tpu/distributed/``): the router rule's socket checks,
    PLUS argument-less barrier-style waits — ``.wait()`` / ``.join()``
    / ``.get()`` / ``.acquire()`` with no positional argument and no
    ``timeout=`` keyword. A process group's failure mode is the
    unbounded collective wait (a dead member wedges every survivor),
    so every rendezvous, queue pop, thread join, and lock acquire in
    the distributed layer must carry an explicit deadline the group
    supervisor can act on (docs/RESILIENCE.md "Multi-host"). Calls
    with any positional argument pass (``d.get(key)``,
    ``done.wait(5)``); a genuinely-unbounded wait that is safe
    suppresses per line with a reason. The same check name also
    covers Condition hygiene in ``serving/`` and ``fleet/``: a
    ``.wait()`` with no timeout on an attribute assigned from
    ``threading.Condition(...)`` is flagged there too — a missed
    notify (e.g. a producer dying between append and notify) wedges
    the waiter forever, so every condition wait must be a
    predicate loop with a bounded wait.

``blocking-under-lock``
    Blocking work while a lock is held, in the concurrent host-side
    packages (``serving/``, ``fleet/``, ``distributed/``): inside a
    ``with <something named *lock*>:`` frame (or a ``with`` on a
    ``threading.Condition`` attribute, which acquires its lock), flag
    ``time.sleep``, ``pickle.dumps/loads/dump/load``,
    ``subprocess.run/Popen/check_*/call``, socket operations
    (``send``/``sendall``/``recv*``/``accept``/``connect``), builtin
    ``open()``, and the fleet framing wrappers ``send_msg`` /
    ``recv_msg``. Work done under a lock serializes every thread that
    touches that lock — a slow pickle under the router lock stalls
    all routing, and socket IO under a lock is the PR-5 breaker
    deadlock shape one hop away. Move the blocking work outside the
    critical section (snapshot under the lock, do IO after release),
    or suppress per line with a reason when holding the lock IS the
    protocol (e.g. one-in-flight-per-connection RPC framing).

``kv-alias``
    A direct functional page write — ``X.at[...].set(...)`` / ``.add``
    / any other ``.at`` update method — in a module under
    ``perceiver_tpu/serving/`` other than ``serving/decode.py`` or
    ``serving/prefix_cache.py``. With content-addressed prefix caching
    (ISSUE 18) a KV page in the paged arena may be aliased by many
    streams and by the prefix index; the copy-on-write discipline
    (``ensure_private_page`` before any write) lives entirely in those
    two modules, and a page write anywhere else in the serving layer
    bypasses it — silently corrupting every other stream sharing the
    page. Genuinely non-arena ``.at`` updates in serving code suppress
    per line with a reason.

``tenant-label-discipline``
    Metric label sites (``.labels(...)``) and typed event emissions
    (``emit("...", ...)``) in the multi-tenant planes — ``fleet/``,
    ``serving/decode.py``, ``serving/batcher.py`` — without a
    ``tenant=`` keyword. Noisy-neighbor isolation is only *provable*
    if every observability series in the shared-pool path attributes
    its samples to a tenant (docs/OBSERVABILITY.md "Tenant labels");
    an unlabeled series silently merges all tenants and hides exactly
    the starvation the quotas exist to prevent. Series that are
    genuinely tenant-free (per-replica breaker gauges, aggregate
    outcome counters that a tenant-split sibling series covers)
    suppress per line with a reason naming the covering series.

Tracing detection is local and conservative: functions decorated with
``jax.jit`` / ``partial(jax.jit, ...)``, functions passed to a
``jax.jit(...)`` call anywhere in the module, and everything nested
inside them. Cross-module propagation (a jitted caller invoking a
helper from another file) is out of scope — the graph passes cover
that end via the lowered module itself.

Suppress any finding by putting ``graphcheck: ignore`` in a comment on
the offending line.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set

from perceiver_tpu.analysis.report import Report, Violation

SUPPRESS_MARKER = "graphcheck: ignore"

_TIME_CALLS = {"time", "perf_counter", "monotonic", "time_ns",
               "perf_counter_ns", "monotonic_ns", "process_time"}
# attribute accesses that read static metadata, not traced values
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}


def _is_jit_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return False


def _is_partial_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "partial"
    if isinstance(node, ast.Attribute):
        return node.attr == "partial"
    return False


def _is_jit_decorator(dec: ast.AST) -> bool:
    if _is_jit_expr(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            return True
        if _is_partial_expr(dec.func):
            return any(_is_jit_expr(a) for a in dec.args)
    return False


def _attr_root(node: ast.AST) -> Optional[str]:
    """``np.random.normal`` → ``"np"``; bare names → the name."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


class _Imports(ast.NodeVisitor):
    """Module alias map for the handful of modules the rules care
    about. ``top_level`` records what the module imports at its top
    scope (for the ops mixing rule)."""

    def __init__(self):
        self.numpy: Set[str] = set()
        self.jnp: Set[str] = set()
        self.time: Set[str] = set()
        self.random: Set[str] = set()
        self.datetime: Set[str] = set()
        self.top_level: Set[str] = set()
        self._depth = 0

    def visit_FunctionDef(self, node):
        self._depth += 1
        self.generic_visit(node)
        self._depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def _record(self, module: str, alias: str) -> None:
        bucket = {"numpy": self.numpy, "jax.numpy": self.jnp,
                  "time": self.time, "random": self.random,
                  "datetime": self.datetime}.get(module)
        if bucket is not None:
            bucket.add(alias)
            if self._depth == 0:
                self.top_level.add(module)

    def visit_Import(self, node):
        for a in node.names:
            self._record(a.name, a.asname or a.name.split(".")[0])

    def visit_ImportFrom(self, node):
        if node.module == "jax":
            for a in node.names:
                if a.name == "numpy":
                    self._record("jax.numpy", a.asname or "numpy")


def _jit_called_names(tree: ast.AST) -> Set[str]:
    """Function names passed to a ``jax.jit(fn, ...)``-style call."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                and node.args and isinstance(node.args[0], ast.Name):
            names.add(node.args[0].id)
    return names


def _traced_param_names(node: ast.AST) -> Iterable[str]:
    a = node.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        if arg.arg != "self":
            yield arg.arg


def _names_outside_static_attrs(node: ast.AST) -> Set[str]:
    """Names referenced in ``node``, skipping subtrees hanging off
    static-metadata attributes (``x.shape[0]`` reads no traced data)."""
    found: Set[str] = set()

    def walk(n):
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Name):
            found.add(n.id)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return found


class _TracedChecker:
    """Applies the traced-context rules inside one jit-traced function
    (and its nested defs, whose params are traced too)."""

    def __init__(self, imports: _Imports, path: str):
        self.imports = imports
        self.path = path
        self.violations: List[Violation] = []

    def _add(self, check: str, node: ast.AST, message: str) -> None:
        self.violations.append(Violation(
            check=check, where=f"{self.path}:{node.lineno}",
            message=message))

    def check(self, fn: ast.AST) -> List[Violation]:
        self._walk(fn, set(_traced_param_names(fn)))
        return self.violations

    def _walk(self, node: ast.AST, params: Set[str]) -> None:
        for child in ast.iter_child_nodes(node):
            child_params = params
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_params = params | set(_traced_param_names(child))
            if isinstance(child, ast.Call):
                self._check_call(child, params)
            self._walk(child, child_params)

    def _check_call(self, call: ast.Call, params: Set[str]) -> None:
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not call.args:
            self._add("jit-host-sync", call,
                      ".item() inside a jit-traced function — a "
                      "device→host sync that fails under trace; thread "
                      "the value out of the jitted computation instead")
            return
        if isinstance(func, ast.Name) and func.id in ("float", "int",
                                                      "bool") \
                and call.args:
            touched = _names_outside_static_attrs(call.args[0]) & params
            if touched:
                self._add("jit-host-sync", call,
                          f"{func.id}() applied to traced value(s) "
                          f"{sorted(touched)} inside a jit-traced "
                          "function — concretization error under "
                          "trace; use jnp casts/ops instead")
            return
        root = _attr_root(func)
        if root is None:
            return
        chain = _attr_chain(func)
        if root in self.imports.numpy:
            if len(chain) >= 3 and chain[1] == "random":
                self._add("jit-python-rng-time", call,
                          f"{'.'.join(chain)}() inside a jit-traced "
                          "function — host RNG runs once at trace time "
                          "and freezes; use jax.random with a threaded "
                          "key")
            else:
                self._add("jit-host-sync", call,
                          f"{'.'.join(chain)}() inside a jit-traced "
                          "function — numpy concretizes traced values; "
                          "use the jax.numpy equivalent")
            return
        if root in self.imports.time and chain[-1] in _TIME_CALLS:
            self._add("jit-python-rng-time", call,
                      f"{'.'.join(chain)}() inside a jit-traced "
                      "function — evaluated once at trace time, then "
                      "constant; time outside the jitted step")
            return
        if root in self.imports.random:
            self._add("jit-python-rng-time", call,
                      f"{'.'.join(chain)}() inside a jit-traced "
                      "function — Python RNG runs at trace time and "
                      "freezes; use jax.random with a threaded key")
            return
        if root in self.imports.datetime and chain[-1] in ("now",
                                                           "utcnow",
                                                           "today"):
            self._add("jit-python-rng-time", call,
                      f"{'.'.join(chain)}() inside a jit-traced "
                      "function — trace-time constant; stamp outside "
                      "the jitted step")


def _is_dataclass_decorator(dec: ast.AST) -> bool:
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Name):
        return target.id == "dataclass"
    if isinstance(target, ast.Attribute):
        return target.attr == "dataclass"
    return False


def _check_impl_fields(cls: ast.ClassDef, path: str) -> List[Violation]:
    fields = [(stmt.target.id, stmt.lineno) for stmt in cls.body
              if isinstance(stmt, ast.AnnAssign)
              and isinstance(stmt.target, ast.Name)
              and stmt.target.id.endswith("_impl")]
    if not fields:
        return []
    post = next((stmt for stmt in cls.body
                 if isinstance(stmt, ast.FunctionDef)
                 and stmt.name == "__post_init__"), None)
    validated: Set[str] = set()
    if post is not None:
        # only the `self.<field> not in <valid set>` form counts: a
        # positive `in` test is how the feature guards are phrased
        # (e.g. "dropout unsupported for impl in (...)"), which must
        # not satisfy the domain-validation requirement
        for node in ast.walk(post):
            if isinstance(node, ast.Compare) and any(
                    isinstance(op, ast.NotIn) for op in node.ops):
                left = node.left
                if isinstance(left, ast.Attribute) \
                        and isinstance(left.value, ast.Name) \
                        and left.value.id == "self":
                    validated.add(left.attr)
    out = []
    for name, lineno in fields:
        if name not in validated:
            out.append(Violation(
                check="impl-field-validation", where=f"{path}:{lineno}",
                message=f"dataclass {cls.name}.{name} is an enum-like "
                        "impl field with no membership validation in "
                        f"{cls.name}.__post_init__ — an invalid value "
                        "only fails deep inside a jit trace; validate "
                        "at config time"))
    return out


def _check_uncached_compiles(tree: ast.AST, path: str) -> List[Violation]:
    """``uncached-compile``: raw ``.lower().compile()`` outside the
    cache package (see module docstring). Matches the chained form and
    the two-statement form (``lowered = f.lower(...); lowered.
    compile()``) via a module-wide name scan — conservative enough
    that ``re.compile`` and friends never match (the receiver must be
    a lowering)."""
    lowered_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and isinstance(node.value.func, ast.Attribute) \
                and node.value.func.attr == "lower":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    lowered_names.add(tgt.id)
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "compile"):
            continue
        recv = node.func.value
        chained = (isinstance(recv, ast.Call)
                   and isinstance(recv.func, ast.Attribute)
                   and recv.func.attr == "lower")
        named = isinstance(recv, ast.Name) and recv.id in lowered_names
        if chained or named:
            out.append(Violation(
                check="uncached-compile", where=f"{path}:{node.lineno}",
                message="raw .lower().compile() outside "
                        "perceiver_tpu/cache/ — route AOT compiles "
                        "through perceiver_tpu.cache (aot_compile / "
                        "compile_lowered) so warm starts deserialize "
                        "instead of recompiling, or suppress with "
                        "'graphcheck: ignore' and a reason"))
    return out


_BROAD_EXCEPTIONS = {"Exception", "BaseException"}


def _has_reason_comment(lines: List[str], lineno: int) -> bool:
    """A non-empty ``#`` comment on the 1-based line counts as the
    required reason (naive scan is fine: the flagged lines hold only
    ``except ...:`` / ``pass`` / ``...``, never ``#`` in a string)."""
    try:
        line = lines[lineno - 1]
    except IndexError:
        return False
    head, sep, comment = line.partition("#")
    return bool(sep) and bool(comment.strip())


def _is_broad_type(node: Optional[ast.AST]) -> bool:
    if node is None:
        return True  # bare except
    if isinstance(node, ast.Tuple):
        return any(_is_broad_type(e) for e in node.elts)
    if isinstance(node, ast.Name):
        return node.id in _BROAD_EXCEPTIONS
    if isinstance(node, ast.Attribute):
        return node.attr in _BROAD_EXCEPTIONS
    return False


def _check_silent_swallow(tree: ast.AST, lines: List[str],
                          path: str) -> List[Violation]:
    """``silent-swallow``: see module docstring. A bare ``except:`` is
    flagged regardless of body; a broad typed handler only when its
    body is pure ``pass``/``...``. A reason comment on the ``except``
    line or any body line clears it."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        bare = node.type is None
        swallows = all(
            isinstance(stmt, ast.Pass)
            or (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis)
            for stmt in node.body)
        if not (bare or (_is_broad_type(node.type) and swallows)):
            continue
        check_lines = [node.lineno] + [s.lineno for s in node.body]
        if any(_has_reason_comment(lines, ln) for ln in check_lines):
            continue
        what = ("bare except:" if bare
                else "except Exception: pass")
        out.append(Violation(
            check="silent-swallow", where=f"{path}:{node.lineno}",
            message=f"{what} silently discards the failure — narrow "
                    "the exception type, handle it visibly, or add a "
                    "reason comment on the except/pass line (or "
                    "'graphcheck: ignore') explaining why discarding "
                    "is correct"))
    return out


# serving/engine.py: the sync-free dispatch contract (docs/SERVING.md)
_ENGINE_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_NUMPY_CONVERSIONS = {"asarray", "array", "copy", "ascontiguousarray"}


def _check_engine_syncs(tree: ast.AST, imports: _Imports,
                        path: str) -> List[Violation]:
    """``serving-host-sync``: no device→host synchronization anywhere
    in the serving engine module (see module docstring)."""
    out: List[Violation] = []

    def add(node, what, hint):
        out.append(Violation(
            check="serving-host-sync", where=f"{path}:{node.lineno}",
            message=f"{what} in serving/engine.py — the engine "
                    "dispatch path must never synchronize on device "
                    f"values; {hint}"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in _ENGINE_SYNC_ATTRS:
            add(node, f".{func.attr}()",
                "materialize results in serving/api.py instead")
            continue
        chain = _attr_chain(func)
        if chain and chain[-1] == "device_get":
            add(node, "device_get()",
                "hand device arrays to the consumer layer instead")
            continue
        root = _attr_root(func)
        if root in imports.numpy and len(chain) == 2 \
                and chain[1] in _NUMPY_CONVERSIONS:
            add(node, f"{'.'.join(chain)}() on a potential device array",
                "numpy conversion forces a transfer — convert in "
                "serving/api.materialize")
    return out


# fleet/: every blocking socket op needs a reachable deadline
_BLOCKING_RECV_ATTRS = {"recv", "recv_into", "recvfrom", "accept"}


def _receiver_key(func: ast.AST) -> Optional[str]:
    """``self._sock.recv`` → ``"self._sock"`` (the dotted receiver the
    method is called on), None for non-name receivers."""
    chain = _attr_chain(func)
    return ".".join(chain[:-1]) if len(chain) >= 2 else None


def _check_router_blocking_io(tree: ast.AST, path: str) -> List[Violation]:
    """``router-blocking-io``: see the module docstring. The receiver
    match is name-based and module-wide — one ``settimeout`` anywhere
    on the same dotted receiver clears its reads, which is exactly the
    discipline ``fleet/rpc.py`` follows (re-assert the timeout before
    every framed read)."""
    out: List[Violation] = []
    with_timeout: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "settimeout":
            key = _receiver_key(node.func)
            if key is not None:
                with_timeout.add(key)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) \
                and func.attr in _BLOCKING_RECV_ATTRS:
            key = _receiver_key(func)
            if key is not None and key not in with_timeout:
                out.append(Violation(
                    check="router-blocking-io",
                    where=f"{path}:{node.lineno}",
                    message=f"blocking {key}.{func.attr}() without a "
                            f"settimeout on {key!r} anywhere in the "
                            "module — a stalled peer would hang this "
                            "fleet hot path forever; set a deadline "
                            "so the router can eject and retry on a "
                            "sibling"))
            continue
        chain = _attr_chain(func)
        if chain and chain[-1] == "create_connection":
            has_timeout = any(kw.arg == "timeout"
                              for kw in node.keywords) \
                or len(node.args) >= 2
            if not has_timeout:
                out.append(Violation(
                    check="router-blocking-io",
                    where=f"{path}:{node.lineno}",
                    message="socket.create_connection without a "
                            "timeout blocks indefinitely on an "
                            "unresponsive replica — pass timeout= so "
                            "connect attempts respect the fleet's "
                            "failover deadline"))
    return out


# distributed/: socket discipline + no argument-less barrier waits
_BARRIER_WAIT_ATTRS = {"wait", "join", "get", "acquire"}


def _check_distributed_blocking_io(tree: ast.AST,
                                   path: str) -> List[Violation]:
    """``distributed-blocking-io``: see the module docstring. Socket
    checks mirror ``router-blocking-io`` (same receiver-key match);
    the barrier-wait check is purely syntactic — no positional args
    and no ``timeout=`` keyword means the call can block forever."""
    out: List[Violation] = []
    for v in _check_router_blocking_io(tree, path):
        out.append(Violation(
            check="distributed-blocking-io", where=v.where,
            message=v.message.replace(
                "fleet hot path", "distributed code path")))
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BARRIER_WAIT_ATTRS):
            continue
        if node.args or any(kw.arg == "timeout"
                            for kw in node.keywords):
            continue
        key = _receiver_key(node.func) or "<expr>"
        out.append(Violation(
            check="distributed-blocking-io",
            where=f"{path}:{node.lineno}",
            message=f"argument-less {key}.{node.func.attr}() in a "
                    "distributed module can block forever — a dead "
                    "group member must surface as a typed timeout the "
                    "supervisor can re-form on, never a wedged "
                    "barrier; pass a timeout (or suppress with "
                    "'graphcheck: ignore' and a reason)"))
    return out


# serving/+fleet/+distributed/: no blocking work under a held lock
_LOCKISH_NAME_RE = re.compile(r"lock|mutex", re.IGNORECASE)
_PICKLE_CALLS = {"dumps", "loads", "dump", "load"}
_SUBPROCESS_CALLS = {"run", "Popen", "check_output", "check_call",
                     "call"}
_SOCKET_BLOCKING_ATTRS = {"sendall", "send", "recv", "recv_into",
                          "recvfrom", "accept", "connect"}
_FRAMING_CALLS = {"send_msg", "recv_msg"}


def _condition_attrs(tree: ast.AST) -> Set[str]:
    """Final names assigned from a ``threading.Condition(...)`` call
    anywhere in the module (``self._not_empty = threading.Condition(
    self._lock)`` → ``"_not_empty"``). Module-wide on purpose: a
    subclass method using a base-class Condition still resolves."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        chain = _attr_chain(node.value.func)
        if not chain or chain[-1] != "Condition":
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute):
                out.add(tgt.attr)
            elif isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


def _check_blocking_under_lock(tree: ast.AST,
                               path: str) -> List[Violation]:
    """``blocking-under-lock``: see the module docstring. A lock frame
    is a ``with`` whose context expression's final name matches
    ``lock``/``mutex`` (case-insensitive) or is a known Condition
    attribute; nested function bodies reset the held set (they run
    later, on whatever thread calls them)."""
    cond_attrs = _condition_attrs(tree)
    out: List[Violation] = []

    def lockish(expr: ast.AST) -> Optional[str]:
        chain = _attr_chain(expr)
        if not chain:
            return None
        final = chain[-1]
        if _LOCKISH_NAME_RE.search(final) or final in cond_attrs:
            return ".".join(chain)
        return None

    def classify(call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "open() file IO"
            if func.id in _FRAMING_CALLS:
                return f"{func.id}() framed socket IO"
            return None
        chain = _attr_chain(func)
        if not chain or not isinstance(func, ast.Attribute):
            return None
        root, final = chain[0], chain[-1]
        if final in _FRAMING_CALLS:
            return f"{'.'.join(chain)}() framed socket IO"
        if root == "time" and final == "sleep":
            return "time.sleep()"
        if root == "pickle" and final in _PICKLE_CALLS:
            return f"pickle.{final}() serialization"
        if root == "subprocess" and final in _SUBPROCESS_CALLS:
            return f"subprocess.{final}()"
        if final in _SOCKET_BLOCKING_ATTRS and len(chain) >= 2:
            return f"{'.'.join(chain)}() socket IO"
        return None

    def walk(node: ast.AST, held) -> None:
        for child in ast.iter_child_nodes(node):
            child_held = held
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                child_held = ()
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                locks = tuple(
                    (name, child.lineno) for item in child.items
                    for name in (lockish(item.context_expr),)
                    if name is not None)
                child_held = held + locks
            elif isinstance(child, ast.Call) and held:
                what = classify(child)
                if what is not None:
                    lock_name, lock_line = held[-1]
                    out.append(Violation(
                        check="blocking-under-lock",
                        where=f"{path}:{child.lineno}",
                        message=f"{what} while holding {lock_name} "
                                f"(acquired line {lock_line}) — "
                                "blocking work under a lock "
                                "serializes every thread on that "
                                "lock and is one callback away from "
                                "the breaker-deadlock shape "
                                "(docs/RESILIENCE.md); snapshot "
                                "under the lock and do the blocking "
                                "work after release, or suppress "
                                "with 'graphcheck: ignore' and a "
                                "reason if holding the lock is the "
                                "protocol"))
            walk(child, child_held)

    walk(tree, ())
    return out


def _check_condition_waits(tree: ast.AST, path: str) -> List[Violation]:
    """Condition hygiene (emitted as ``distributed-blocking-io``; see
    module docstring): ``.wait()`` with no positional argument and no
    ``timeout=`` on an attribute assigned from
    ``threading.Condition(...)``."""
    cond_attrs = _condition_attrs(tree)
    out: List[Violation] = []
    if not cond_attrs:
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"):
            continue
        chain = _attr_chain(node.func)
        if len(chain) < 2 or chain[-2] not in cond_attrs:
            continue
        if node.args or any(kw.arg == "timeout"
                            for kw in node.keywords):
            continue
        cond = ".".join(chain[:-1])
        out.append(Violation(
            check="distributed-blocking-io",
            where=f"{path}:{node.lineno}",
            message=f"{cond}.wait() with no timeout — a missed "
                    "notify (producer dying between append and "
                    "notify) wedges this waiter forever; wait in a "
                    "predicate loop with a bounded timeout so the "
                    "thread can re-check shutdown flags "
                    "(docs/RESILIENCE.md), or suppress with "
                    "'graphcheck: ignore' and a reason"))
    return out


# metric registration sites: one naming convention for all planes
_METRIC_KINDS = {"counter", "gauge", "histogram"}
_METRIC_NAME_RE = re.compile(r"^(serving|training|fleet)_[a-z0-9_]+$")


def _check_metrics_conventions(tree: ast.AST,
                               path: str) -> List[Violation]:
    """``metrics-conventions``: see the module docstring. Only
    string-literal first arguments are checked — a computed name is a
    different smell, but not one an AST pass can validate."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_KINDS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        kind, name = node.func.attr, node.args[0].value
        problems = []
        if not _METRIC_NAME_RE.match(name):
            problems.append(
                "must be snake_case with a serving_/training_/fleet_ "
                "plane prefix")
        if kind == "counter" and not name.endswith("_total"):
            problems.append("counters must end in _total")
        if kind != "counter" and name.endswith("_total"):
            problems.append(f"{kind}s must not end in _total "
                            "(reserved for counters)")
        for problem in problems:
            out.append(Violation(
                check="metrics-conventions",
                where=f"{path}:{node.lineno}",
                message=f"metric {name!r} registered via .{kind}() — "
                        f"{problem}; one naming scheme keeps the "
                        "merged fleet exposition collision-free and "
                        "rate()-able (docs/OBSERVABILITY.md)"))
    return out


def _is_pjit_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "pjit"
    if isinstance(node, ast.Attribute):
        return node.attr == "pjit"
    return False


_SHARDING_KWARGS = {"in_shardings", "out_shardings"}


def _check_unsharded_pjit(tree: ast.AST, path: str) -> List[Violation]:
    """``unsharded-pjit``: jit/pjit in the SPMD modules without
    explicit in_shardings AND out_shardings (see module docstring).
    Covers the call form, the ``@partial(jax.jit, ...)`` decorator,
    and the bare ``@jax.jit`` decorator."""
    out: List[Violation] = []

    def flag(lineno: int, missing) -> None:
        out.append(Violation(
            check="unsharded-pjit", where=f"{path}:{lineno}",
            message=f"jit/pjit without explicit {'/'.join(missing)} "
                    "in an SPMD module — silent sharding propagation "
                    "is how replication sneaks in; declare the layout "
                    "at the pjit boundary (parallel/sharding.py specs) "
                    "or suppress with 'graphcheck: ignore' and a "
                    "reason"))

    for node in ast.walk(tree):
        kws = None
        if isinstance(node, ast.Call):
            if _is_jit_expr(node.func) or _is_pjit_expr(node.func):
                kws = node.keywords
            elif _is_partial_expr(node.func) and any(
                    _is_jit_expr(a) or _is_pjit_expr(a)
                    for a in node.args):
                kws = node.keywords
        if kws is None:
            continue
        missing = sorted(_SHARDING_KWARGS
                         - {kw.arg for kw in kws if kw.arg})
        if missing:
            flag(node.lineno, missing)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                # bare @jax.jit — the Call forms were handled above
                if not isinstance(dec, ast.Call) and (
                        _is_jit_expr(dec) or _is_pjit_expr(dec)):
                    flag(dec.lineno, sorted(_SHARDING_KWARGS))
    return out


# serving/: CoW discipline — page writes only in the two CoW-aware
# modules (decode.py enforces ensure_private_page; prefix_cache.py
# defines it)
_AT_UPDATE_METHODS = {"set", "add", "subtract", "multiply", "divide",
                      "power", "min", "max", "apply"}
_KV_ALIAS_EXEMPT = ("serving/decode.py", "serving/prefix_cache.py")


def _check_kv_alias(tree: ast.AST, path: str) -> List[Violation]:
    """``kv-alias``: see the module docstring. The match is the exact
    JAX functional-update shape — a call on an attribute of an
    ``.at[...]`` subscript — so ordinary dict/list ``.add``/``.set``
    calls never trip it."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _AT_UPDATE_METHODS):
            continue
        sub = node.func.value
        if not (isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "at"):
            continue
        out.append(Violation(
            check="kv-alias",
            where=f"{path}:{node.lineno}",
            message=f".at[...].{node.func.attr}(...) page write outside "
                    "the CoW-aware modules — KV pages may be aliased by "
                    "the prefix index and other streams (refcount > 1), "
                    "and only serving/decode.py (via "
                    "ensure_private_page) and serving/prefix_cache.py "
                    "uphold the copy-on-write discipline; route the "
                    "write through the engine, or mark the line "
                    "'graphcheck: ignore' with a reason if the target "
                    "is provably not the paged arena"))
    return out


# multi-tenant observability: every label/emit site in these planes
# must attribute to a tenant (or carry a reasoned suppression)
_TENANT_LABEL_FILES = ("serving/decode.py", "serving/batcher.py")


def _check_tenant_label_discipline(tree: ast.AST,
                                   path: str) -> List[Violation]:
    """``tenant-label-discipline``: see the module docstring. Matches
    ``<anything>.labels(...)`` and ``emit("<type>", ...)`` /
    ``<anything>.emit("<type>", ...)`` calls; only string-literal
    event types are checked (computed types are a different smell)."""
    out: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        is_labels = isinstance(func, ast.Attribute) \
            and func.attr == "labels"
        is_emit = ((isinstance(func, ast.Attribute)
                    and func.attr == "emit")
                   or (isinstance(func, ast.Name) and func.id == "emit"))
        if not (is_labels or is_emit):
            continue
        if is_emit and not (node.args
                            and isinstance(node.args[0], ast.Constant)
                            and isinstance(node.args[0].value, str)):
            continue
        if any(kw.arg == "tenant" for kw in node.keywords):
            continue
        what = ("metric .labels(...) site" if is_labels
                else f"event emit({node.args[0].value!r}, ...)")
        out.append(Violation(
            check="tenant-label-discipline",
            where=f"{path}:{node.lineno}",
            message=f"{what} without a tenant= label in a multi-tenant "
                    "plane — unlabeled series merge all tenants and "
                    "hide noisy-neighbor starvation "
                    "(docs/OBSERVABILITY.md 'Tenant labels'); add the "
                    "tenant label, or mark the line 'graphcheck: "
                    "ignore' with a reason naming the tenant-split "
                    "series that covers it"))
    return out


def lint_source(src: str, path: str = "<memory>") -> List[Violation]:
    """Lint one module's source. ``path`` is used for reporting and
    for the ops-scoped rule (a path containing ``/ops/``)."""
    tree = ast.parse(src, filename=path)
    imports = _Imports()
    imports.visit(tree)
    violations: List[Violation] = []
    violations.extend(_check_silent_swallow(tree, src.splitlines(), path))
    violations.extend(_check_metrics_conventions(tree, path))

    norm = path.replace(os.sep, "/")
    if norm.endswith("serving/engine.py"):
        violations.extend(_check_engine_syncs(tree, imports, path))
    if "perceiver_tpu/fleet/" in norm:
        violations.extend(_check_router_blocking_io(tree, path))
    if "perceiver_tpu/distributed/" in norm:
        violations.extend(_check_distributed_blocking_io(tree, path))
    if ("perceiver_tpu/serving/" in norm
            or "perceiver_tpu/fleet/" in norm
            or "perceiver_tpu/distributed/" in norm):
        violations.extend(_check_blocking_under_lock(tree, path))
    if "perceiver_tpu/serving/" in norm \
            or "perceiver_tpu/fleet/" in norm:
        violations.extend(_check_condition_waits(tree, path))
    if "perceiver_tpu/serving/" in norm and not norm.endswith(
            _KV_ALIAS_EXEMPT):
        violations.extend(_check_kv_alias(tree, path))
    if "perceiver_tpu/fleet/" in norm \
            or norm.endswith(_TENANT_LABEL_FILES):
        violations.extend(_check_tenant_label_discipline(tree, path))
    if "perceiver_tpu/parallel/" in norm \
            or norm.endswith("perceiver_tpu/training/spmd.py"):
        violations.extend(_check_unsharded_pjit(tree, path))
    if "perceiver_tpu/cache/" not in norm:
        violations.extend(_check_uncached_compiles(tree, path))
    if "/ops/" in norm and {"numpy", "jax.numpy"} <= imports.top_level:
        lineno = next((n.lineno for n in tree.body
                       if isinstance(n, (ast.Import, ast.ImportFrom))), 1)
        violations.append(Violation(
            check="ops-numpy-mix", where=f"{path}:{lineno}",
            message="ops module imports both numpy and jax.numpy at "
                    "top level — keep host-side precompute in np-only "
                    "modules (ops/fourier.py pattern) and traced code "
                    "jnp-only, or mark the line 'graphcheck: ignore' "
                    "with a reason"))

    jit_names = _jit_called_names(tree)
    traced_roots = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in jit_names or any(
                    _is_jit_decorator(d) for d in node.decorator_list):
                traced_roots.append(node)
    # drop roots nested inside another root (checked once, outermost)
    covered = set()
    for root in traced_roots:
        for sub in ast.walk(root):
            if sub is not root and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                covered.add(sub)
    for root in traced_roots:
        if root not in covered:
            violations.extend(
                _TracedChecker(imports, path).check(root))

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
                _is_dataclass_decorator(d) for d in node.decorator_list):
            violations.extend(_check_impl_fields(node, path))

    # per-line suppression
    lines = src.splitlines()
    kept = []
    for v in violations:
        try:
            lineno = int(v.where.rsplit(":", 1)[1])
            if SUPPRESS_MARKER in lines[lineno - 1]:
                continue
        except (IndexError, ValueError):
            pass
        kept.append(v)
    return kept


ALL_RULES = ("jit-host-sync", "jit-python-rng-time", "ops-numpy-mix",
             "impl-field-validation", "serving-host-sync",
             "uncached-compile", "silent-swallow", "router-blocking-io",
             "distributed-blocking-io", "unsharded-pjit",
             "metrics-conventions", "blocking-under-lock", "kv-alias",
             "tenant-label-discipline")


def lint_paths(paths: Iterable[str]) -> Report:
    """Lint every ``.py`` file under the given files/directories."""
    report = Report()
    for rule in ALL_RULES:
        report.ran(rule)
    for path in _expand(paths):
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            report.extend(lint_source(src, path))
        except SyntaxError as e:
            report.add(Violation(
                check="lint-parse", where=f"{path}:{e.lineno or 0}",
                message=f"could not parse: {e.msg}"))
    return report


def _expand(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


_REPO_LINT_DEFAULTS = ("perceiver_tpu", "scripts", "bench.py", "run.py")


def default_lint_paths(repo_root: str) -> List[str]:
    """The tree ``scripts/check.py`` lints by default: the package,
    the scripts, and the entry points. Tests are excluded on purpose —
    they host-sync deliberately to assert on device values."""
    return [os.path.join(repo_root, p) for p in _REPO_LINT_DEFAULTS
            if os.path.exists(os.path.join(repo_root, p))]
