#!/usr/bin/env python
"""Offline Mosaic compile-proof for every Pallas kernel (VERDICT r3
missing #2 — without waiting for the tunnel).

The container ships a LOCAL libtpu, so ``jax.experimental.topologies``
can AOT-compile executables for a real TPU target (``v5e:2x2`` →
device_kind "TPU v5 lite", matching the tunnel chip) with no live
device: XLA runs its full TPU pipeline and Pallas kernels go through
MOSAIC, not the interpreter. This checks the compile-time constraints
that three rounds of interpreter-only testing could not — block/tile
legality, the transposed layout's (D, L) blocking, the bias sublane
trick, the fused-CE grids — and records real-TPU ``memory_analysis``
numbers for each executable.

What it cannot check: runtime behavior/perf. Execution proof still
needs a live chip (the watcher collects it), but a kernel that
compiles cleanly for the exact device_kind removes the biggest risk:
Mosaic rejecting the kernel outright.

Checks (each its own entry in the JSON report):
  * flash_attention, standard (L, D) layout  — D=64, fwd + grad
  * flash_attention, transposed (D, L) layout — D=16, fwd + grad
  * flash_attention with additive key bias (the padding path)
  * pallas fused vocab-CE — fwd + grad (Mosaic bwd kernels)
  * full MLM train step, attention_impl=flash + loss_impl=pallas
    (everything-Mosaic) at bench batch 64
  * full MLM train step at the headline bench rung (batch 512) —
    with memory_analysis: does the top rung fit v5e HBM?

Usage: python scripts/mosaic_aot_check.py [--json OUT]
Env:   MOSAIC_TOPOLOGY (default v5e:2x2)
"""

import argparse
import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["PERCEIVER_TPU_ASSUME_TPU"] = "1"  # Mosaic, not interpreter

import jax

jax.config.update("jax_platforms", "cpu")  # never touches the tunnel

import jax.numpy as jnp
from jax.experimental import topologies


def _sharding():
    topo = topologies.get_topology_desc(
        os.environ.get("MOSAIC_TOPOLOGY", "v5e:2x2"), platform="tpu")
    return (jax.sharding.SingleDeviceSharding(topo.devices[0]),
            topo.devices[0].device_kind)


def _sds(shape, dtype, sh):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)


def _mem(compiled):
    try:
        m = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001
        return {"error": str(e)[:120]}
    out = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes"):
        v = getattr(m, k, None)
        if v is not None:
            out[k.replace("_in_bytes", "_mb")] = round(v / 2**20, 1)
    if "argument_size_mb" in out and "temp_size_mb" in out:
        out["approx_peak_mb"] = round(out["argument_size_mb"]
                                      + out["temp_size_mb"], 1)
    return out


def _check(name, fn, *args):
    t0 = time.monotonic()
    try:
        compiled = jax.jit(fn).lower(*args).compile()  # graphcheck: ignore — Mosaic compile probe, compilation IS the measurement
        txt = compiled.as_text()
        entry = {
            "ok": True,
            "mosaic_custom_call": "custom-call" in txt,
            "compile_s": round(time.monotonic() - t0, 1),
            "memory": _mem(compiled),
        }
    except Exception as e:  # noqa: BLE001
        entry = {"ok": False, "error": f"{type(e).__name__}: "
                 f"{str(e)[:400]}",
                 "compile_s": round(time.monotonic() - t0, 1)}
    print(f"[{name}] {entry}", file=sys.stderr, flush=True)
    return name, entry


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="logs/MOSAIC_AOT_r04.json")
    args = ap.parse_args()

    sh, device_kind = _sharding()
    report = {"device_kind": device_kind,
              "topology": os.environ.get("MOSAIC_TOPOLOGY", "v5e:2x2"),
              "note": ("AOT compile via local libtpu against a TPU "
                       "TopologyDescription — no live device; Mosaic "
                       "compiles the Pallas kernels (interpret=False "
                       "via PERCEIVER_TPU_ASSUME_TPU). Execution "
                       "proof still requires a chip."),
              "checks": {}}

    from perceiver_tpu.ops.pallas_attention import flash_attention

    def flash_grad(q, k, v):
        return jax.grad(lambda q, k, v: flash_attention(q, k, v)
                        .astype(jnp.float32).sum())(q, k, v)

    # standard layout: D=64 (e.g. 8-head/512-channel shapes)
    q64 = _sds((2, 8, 512, 64), jnp.bfloat16, sh)
    # transposed layout: D=16 — EVERY 64-channel/4-head BASELINE
    # config; the layout with the untested sublane tricks
    q16 = _sds((2, 4, 512, 16), jnp.bfloat16, sh)
    bias = _sds((2, 512), jnp.float32, sh)

    checks = [
        ("flash_std_fwd",
         lambda q, k, v: flash_attention(q, k, v), q64, q64, q64),
        ("flash_std_grad", flash_grad, q64, q64, q64),
        ("flash_transposed_fwd",
         lambda q, k, v: flash_attention(q, k, v), q16, q16, q16),
        ("flash_transposed_grad", flash_grad, q16, q16, q16),
        ("flash_bias_fwd",
         lambda q, k, v, b: flash_attention(q, k, v, bias=b),
         q16, q16, q16, bias),
    ]

    from perceiver_tpu.ops.pallas_ce import pallas_linear_cross_entropy

    rows, c, vocab = 1024, 64, 10003
    lp = {"w": _sds((c, vocab), jnp.float32, sh),
          "b": _sds((vocab,), jnp.float32, sh)}
    h = _sds((rows, c), jnp.bfloat16, sh)
    y = _sds((rows,), jnp.int32, sh)
    wt = _sds((rows,), jnp.float32, sh)

    checks.append(("pallas_ce_fwd",
                   lambda lp, h, y, wt: pallas_linear_cross_entropy(
                       lp, h, y, wt), lp, h, y, wt))
    checks.append(("pallas_ce_grad",
                   lambda lp, h, y, wt: jax.grad(
                       lambda lp, h: pallas_linear_cross_entropy(
                           lp, h, y, wt).astype(jnp.float32),
                       argnums=(0, 1))(lp, h), lp, h, y, wt))

    for item in checks:
        name, entry = _check(item[0], item[1], *item[2:])
        report["checks"][name] = entry

    # --- full train steps: everything-Mosaic MLM ----------------------
    import optax

    from perceiver_tpu.ops.policy import Policy
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    def train_step_check(name, batch_size, **task_kw):
        task = MaskedLanguageModelTask(
            vocab_size=10003, max_seq_len=512, **task_kw)
        model = task.build()
        policy = Policy.bf16()
        params = jax.eval_shape(lambda: model.init(jax.random.key(0)))
        tx = optax.adamw(1e-3)
        opt_state = jax.eval_shape(tx.init, params)
        put = lambda t: jax.tree.map(  # noqa: E731
            lambda x: _sds(x.shape, x.dtype, sh), t)
        batch = {"input_ids": _sds((batch_size, 512), jnp.int32, sh),
                 "pad_mask": _sds((batch_size, 512), jnp.bool_, sh)}
        rng = jax.ShapeDtypeStruct((), jax.random.key(0).dtype,
                                   sharding=sh)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, batch, rng):
            def loss_fn(p):
                loss, _ = task.loss_and_metrics(
                    model, p, batch, rng=rng, deterministic=False,
                    policy=policy)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        nm, entry = _check(name, step, put(params), put(opt_state),
                           batch, rng)
        report["checks"][nm] = entry

    train_step_check("mlm_step_flash_pallasce_b64", 64,
                     attention_impl="flash", loss_impl="pallas")
    train_step_check("mlm_step_default_b512", 512, loss_impl="packed")

    ok = sum(1 for c in report["checks"].values() if c.get("ok"))
    report["summary"] = f"{ok}/{len(report['checks'])} compiled"
    out = json.dumps(report, indent=1)
    print(out)
    with open(args.json, "w") as f:
        f.write(out + "\n")


if __name__ == "__main__":
    main()
