"""``scripts/chaos.py --fast`` as a literal subprocess gate — the
check.py pattern (ISSUE 5 satellite): the tier-1 suite proves a fresh
process, armed only through the ``PERCEIVER_FAULTS`` env seam,
survives its fault matrix subset and emits well-formed bench.py-format
JSON."""

import json
import os
import subprocess
import sys


def test_chaos_fast_matrix_survives():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "chaos.py"),
         "--fast"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, f"\n{proc.stdout}\n{proc.stderr}"

    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    by_metric = {ln["metric"]: ln for ln in lines}
    # bench.py-format records, every scenario survived
    for line in lines:
        assert {"metric", "value", "unit", "vs_baseline",
                "detail"} <= set(line)
    assert by_metric["chaos_matrix"]["value"] == 1.0
    scenarios = [ln for ln in lines if ln["metric"] != "chaos_matrix"]
    assert len(scenarios) >= 2
    assert all(ln["value"] == 1.0 for ln in scenarios)
    # the faults really fired (survival by inertness doesn't count)
    assert all(ln["detail"]["faults_fired"] for ln in scenarios)
