"""Speculative decoding (ISSUE 19): the rejection rule, the draft
policy, and the engine's verify-in-one-step path.

The load-bearing properties:

- **rejection rule** — :func:`speculative_accept` emits tokens
  distributed EXACTLY as sampling the target alone (seeded chi-square
  over a tiny vocab), and :func:`greedy_accept` is its one-hot
  degeneration: greedy speculative decode is token-exact against
  non-speculative decode by construction, including mid-window
  rejection and full-window acceptance edges;
- **engine parity** — a self-draft speculative engine generates
  bit-identical streams to a plain engine under fp32 AND bf16,
  including streams admitted through a warm prefix-cache hit, with
  zero post-warmup compiles;
- **fallback** — when acceptance collapses (a never-trained draft),
  the stream flips to plain decode, frees its draft pages, and stays
  token-exact;
- **scheduler** — drafted tokens cost real step budget
  (``plan_speculative``), degrading FIFO toward plain decode before
  starving prefill;
- **facades** — the r18 ``AdmissionQueue`` / ``TokenBudgetBatcher``
  names still construct and behave, but warn ``DeprecationWarning``.
"""

import warnings

import numpy as np
import pytest

from perceiver_tpu.ops.policy import Policy
from perceiver_tpu.serving.batcher import (
    AdmissionQueue,
    ContinuousBatchScheduler,
    TokenBudgetBatcher,
)
from perceiver_tpu.serving.decode import (
    DecodeEngine,
    DecodeGeometry,
    DecodeResult,
)
from perceiver_tpu.serving.prefix_cache import PrefixCacheConfig
from perceiver_tpu.serving.speculative import (
    SpeculativeConfig,
    greedy_accept,
    shrink_task,
    speculative_accept,
)
from perceiver_tpu.tasks.mlm import MaskedLanguageModelTask

VOCAB = 110


def tiny_task():
    return MaskedLanguageModelTask(
        vocab_size=VOCAB, max_seq_len=48, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")


def tiny_geometry(**kw):
    base = dict(max_streams=3, num_pages=33, page_size=4,
                max_seq_len=48, max_chunk=4)
    base.update(kw)
    return DecodeGeometry(**base)


# --- greedy_accept edges -----------------------------------------------------


def test_greedy_accept_full_window():
    # every drafted token matches → all accepted + the bonus token
    assert greedy_accept([3, 5, 7], [3, 5, 7, 9]) == (3, 9)


def test_greedy_accept_mid_window_rejection():
    # target disagrees at position 1 → keep [3], emit the target's own
    # choice at the disagreement, drop the rest of the window
    assert greedy_accept([3, 5, 7], [3, 6, 7, 9]) == (1, 6)


def test_greedy_accept_first_token_rejection():
    assert greedy_accept([3, 5], [4, 5, 9]) == (0, 4)


def test_greedy_accept_empty_window_is_plain_decode():
    # k=0 degenerates to one plain greedy step
    assert greedy_accept([], [8]) == (0, 8)


def test_greedy_accept_requires_k_plus_one_targets():
    with pytest.raises(ValueError, match="k\\+1 target"):
        greedy_accept([3, 5], [3, 5])


# --- speculative_accept: the distribution-match property ---------------------


def test_speculative_accept_matches_target_distribution():
    """The classic guarantee, pinned with a seeded chi-square: the
    first emitted token of each window is marginally distributed
    exactly as sampling the target distribution directly, no matter
    how bad the draft is."""
    rng = np.random.default_rng(19)
    v = 4
    # deliberately mismatched draft: it loves token 0, target doesn't
    q = np.array([[0.7, 0.1, 0.1, 0.1]])
    p_rows = np.array([[0.1, 0.4, 0.3, 0.2],
                       [0.25, 0.25, 0.25, 0.25]])
    n = 20_000
    counts = np.zeros(v)
    for _ in range(n):
        d = int(rng.choice(v, p=q[0]))
        _, emitted = speculative_accept([d], q, p_rows, rng)
        counts[emitted[0]] += 1
    expected = n * p_rows[0]
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    # df = 3; chi2 > 16.27 has p < 0.001 — seeded, so deterministic
    assert chi2 < 16.27, (chi2, counts / n, p_rows[0])


def test_speculative_accept_bonus_distribution_on_sure_accept():
    """When draft == target the rule always accepts, and the bonus
    token must follow the target's k+1-th row exactly."""
    rng = np.random.default_rng(7)
    q = np.array([[0.5, 0.5, 0.0, 0.0]])
    p_rows = np.array([[0.5, 0.5, 0.0, 0.0],
                       [0.05, 0.15, 0.35, 0.45]])
    n = 20_000
    counts = np.zeros(4)
    for _ in range(n):
        d = int(rng.choice(4, p=q[0]))
        accepted, emitted = speculative_accept([d], q, p_rows, rng)
        assert accepted == 1 and emitted[0] == d
        counts[emitted[1]] += 1
    nonzero = p_rows[1] > 0
    expected = n * p_rows[1][nonzero]
    chi2 = float(
        ((counts[nonzero] - expected) ** 2 / expected).sum())
    assert counts[~nonzero].sum() == 0
    assert chi2 < 16.27, (chi2, counts / n)


def test_speculative_accept_one_hot_reduces_to_greedy():
    """With one-hot rows the sampled rule is bit-for-bit the greedy
    rule — the bridge that lets the greedy engine claim the theorem's
    token-exactness guarantee."""
    rng = np.random.default_rng(3)
    v = 6

    def one_hot(ids):
        rows = np.zeros((len(ids), v))
        rows[np.arange(len(ids)), ids] = 1.0
        return rows

    cases = [
        ([2, 4], [2, 4, 1]),   # full acceptance → bonus
        ([2, 4], [2, 5, 1]),   # mid-window rejection
        ([2], [3, 1]),         # immediate rejection
    ]
    for draft, target in cases:
        g_acc, g_next = greedy_accept(draft, target)
        s_acc, emitted = speculative_accept(
            draft, one_hot(draft), one_hot(target), rng)
        assert (s_acc, emitted) == (g_acc, draft[:g_acc] + [g_next])


def test_speculative_accept_shape_mismatch_raises():
    rng = np.random.default_rng(0)
    with pytest.raises(ValueError, match="shape mismatch"):
        speculative_accept([1], np.ones((2, 4)) / 4,
                           np.ones((2, 4)) / 4, rng)


# --- draft policy ------------------------------------------------------------


def test_shrink_task_keeps_vocab_and_shrinks_latents():
    task = MaskedLanguageModelTask(
        vocab_size=VOCAB, max_seq_len=48, num_latents=8,
        num_latent_channels=32, num_encoder_layers=2,
        num_encoder_self_attention_layers_per_block=2)
    draft = shrink_task(task)
    assert draft.vocab_size == task.vocab_size
    assert draft.max_seq_len == task.max_seq_len
    assert draft.num_latent_channels == task.num_latent_channels
    assert draft.num_latents == 2  # quartered
    assert draft.num_encoder_layers == 1
    assert draft.num_encoder_self_attention_layers_per_block == 1
    # the min-1 floor and explicit overrides
    assert shrink_task(task, num_latents=5).num_latents == 5
    tiny = MaskedLanguageModelTask(vocab_size=VOCAB, max_seq_len=48,
                                   num_latents=2)
    assert shrink_task(tiny).num_latents == 1


def test_speculative_config_validation():
    with pytest.raises(ValueError, match="fallback_acceptance"):
        SpeculativeConfig(fallback_acceptance=1.5)
    with pytest.raises(ValueError, match="ema_alpha"):
        SpeculativeConfig(ema_alpha=0.0)


def test_geometry_spec_k_validation_and_descriptor():
    with pytest.raises(ValueError, match="spec_k"):
        tiny_geometry(spec_k=-1)
    with pytest.raises(ValueError, match="chunk lanes"):
        tiny_geometry(spec_k=4, max_chunk=4)  # needs k+1 = 5 lanes
    plain = tiny_geometry()
    spec = tiny_geometry(spec_k=3)
    assert "_k" not in plain.descriptor  # legacy keys unchanged
    assert spec.descriptor == plain.descriptor + "_k3"


def test_engine_requires_spec_k_and_config_together():
    with pytest.raises(ValueError):
        DecodeEngine(tiny_task(), geometry=tiny_geometry(spec_k=2),
                     auto_step=False, exec_cache=False)
    with pytest.raises(ValueError):
        DecodeEngine(tiny_task(), geometry=tiny_geometry(),
                     auto_step=False, exec_cache=False,
                     speculative=SpeculativeConfig())


# --- engine parity: speculative vs plain, fp32 + bf16 ------------------------


@pytest.mark.parametrize("policy_name", ["fp32", "bf16"])
def test_self_draft_speculative_token_exact(policy_name):
    """The merge gate: a self-draft speculative engine (acceptance
    ~1.0 — every window fully accepted) and a never-trained-draft
    engine (acceptance ~0.0 — every window rejected and rolled back)
    BOTH generate bit-identical streams to a plain engine, under fp32
    and bf16, across mixed prompt lengths. Params are
    seed-deterministic across engines, so plain-engine output is the
    oracle."""
    policy = getattr(Policy, policy_name)()
    task = tiny_task()
    rng = np.random.default_rng(19)
    prompts = [rng.integers(3, VOCAB, size=n).astype(np.int32)
               for n in (5, 1, 9)]
    MAX_NEW = 8

    def run_engine(spec_cfg, spec_k):
        eng = DecodeEngine(task, geometry=tiny_geometry(spec_k=spec_k),
                           policy=policy, auto_step=False,
                           exec_cache=False, speculative=spec_cfg)
        try:
            handles = [eng.submit(p, max_new_tokens=MAX_NEW)
                       for p in prompts]
            eng.run_until_idle()
            out = []
            for h in handles:
                r = h.result(1.0)
                assert isinstance(r, DecodeResult)
                assert r.finished == "complete"
                out.append(r.tokens)
            assert eng.pool.free_pages == \
                eng.geometry.allocatable_pages
            if eng.draft_pool is not None:
                assert eng.draft_pool.free_pages == \
                    eng.geometry.allocatable_pages
            stats = eng.speculative_stats()
            return out, stats
        finally:
            eng.close(timeout=2.0)

    plain, _ = run_engine(None, 0)
    accepted, stats = run_engine(SpeculativeConfig(), 3)
    assert accepted == plain, (
        f"{policy_name}: self-draft speculative diverged")
    assert stats["acceptance_rate"] == 1.0
    assert stats["drafted_tokens"] > 0
    rejected, rstats = run_engine(
        SpeculativeConfig(draft_task=shrink_task(task), draft_seed=99,
                          fallback_acceptance=0.0), 3)
    assert rejected == plain, (
        f"{policy_name}: rejection rollback leaked into tokens")
    assert rstats["acceptance_rate"] < 0.5


@pytest.mark.parametrize("policy_name", ["fp32", "bf16"])
def test_speculative_warm_prefix_hit_token_exact(policy_name):
    """The acceptance criterion's hardest path: a stream admitted
    through a WARM prefix-cache hit (shared CoW pages for the cached
    span) on a speculative engine must still be token-exact vs a
    plain caching-disabled engine — drafted positions always land
    past the prompt in refcount-1 private pages, so verify rollback
    must never touch the shared chain. Zero compiles after warmup."""
    from tests.test_decode import compile_events

    policy = getattr(Policy, policy_name)()
    task = tiny_task()
    rng = np.random.default_rng(18)
    seed_prompt = rng.integers(3, VOCAB, size=17).astype(np.int32)
    warm_prompt = np.concatenate(
        [seed_prompt[:16], rng.integers(3, VOCAB, size=4)]
    ).astype(np.int32)
    MAX_NEW = 8

    spec_eng = DecodeEngine(
        task, geometry=tiny_geometry(spec_k=3), policy=policy,
        auto_step=False, exec_cache=False,
        speculative=SpeculativeConfig(),
        prefix_cache=PrefixCacheConfig())
    cold_eng = DecodeEngine(task, geometry=tiny_geometry(),
                            policy=policy, auto_step=False,
                            exec_cache=False)
    try:
        h = spec_eng.submit(seed_prompt, max_new_tokens=2)
        spec_eng.run_until_idle()
        assert h.result(1.0).cached_tokens == 0  # publisher ran cold

        hw = spec_eng.submit(warm_prompt, max_new_tokens=MAX_NEW)
        with compile_events() as events:
            spec_eng.run_until_idle()
        assert events == [], f"speculative warm hit recompiled: {events}"
        warm = hw.result(1.0)
        assert isinstance(warm, DecodeResult)
        assert warm.cached_tokens == 16, warm.cached_tokens

        hc = cold_eng.submit(warm_prompt, max_new_tokens=MAX_NEW)
        cold_eng.run_until_idle()
        cold = hc.result(1.0)
        assert warm.tokens == cold.tokens, (
            f"{policy_name}: warm speculative stream diverged: "
            f"{warm.tokens} vs {cold.tokens}")
        stats = spec_eng.speculative_stats()
        assert stats["acceptance_rate"] == 1.0  # self-draft
        assert stats["drafted_tokens"] > 0
    finally:
        spec_eng.close(timeout=2.0)
        cold_eng.close(timeout=2.0)


def test_acceptance_collapse_falls_back_and_frees_draft_pages():
    """A never-trained draft with the default fallback threshold: the
    acceptance EMA collapses, the stream permanently flips to plain
    decode (``spec_fallback``), its draft pages free mid-flight, and
    the output is still token-exact."""
    task = tiny_task()
    rng = np.random.default_rng(5)
    prompt = rng.integers(3, VOCAB, size=6).astype(np.int32)

    plain_eng = DecodeEngine(task, geometry=tiny_geometry(),
                             auto_step=False, exec_cache=False)
    spec_eng = DecodeEngine(
        task, geometry=tiny_geometry(spec_k=3), auto_step=False,
        exec_cache=False,
        speculative=SpeculativeConfig(draft_task=shrink_task(task),
                                      draft_seed=99))
    try:
        hp = plain_eng.submit(prompt, max_new_tokens=12)
        plain_eng.run_until_idle()
        hs = spec_eng.submit(prompt, max_new_tokens=12)
        spec_eng.run_until_idle()
        assert hs.result(1.0).tokens == hp.result(1.0).tokens
        stats = spec_eng.speculative_stats()
        assert stats["fallbacks"] >= 1
        assert stats["acceptance_rate"] < 1.0
        # fallback freed the stream's draft pages mid-flight
        assert spec_eng.draft_pool.free_pages == \
            spec_eng.geometry.allocatable_pages
    finally:
        plain_eng.close(timeout=2.0)
        spec_eng.close(timeout=2.0)


# --- scheduler: drafted tokens cost budget -----------------------------------


def test_plan_speculative_grants_fifo_from_leftover_budget():
    s = ContinuousBatchScheduler(token_budget=8, max_chunk=4)
    # 3 decode rows pre-spend 3; spec extras get the next 5 FIFO
    grants, chunks = s.plan_speculative(3, (3, 3, 3), ())
    assert grants == [3, 2, 0]
    assert chunks == []
    # prefill still gets the head-row >= 1 guarantee after spec spend
    grants, chunks = s.plan_speculative(3, (3, 3), (4,))
    assert grants == [3, 2]
    assert chunks == [1]
    # no budget → engine-default sizing grants everything
    s = ContinuousBatchScheduler(max_chunk=4)
    grants, chunks = s.plan_speculative(2, (3, 1), (4, 2))
    assert grants == [3, 1]
    assert chunks == [4, 2]


def test_plan_chunks_is_the_no_spec_special_case():
    s = ContinuousBatchScheduler(token_budget=6, max_chunk=4)
    assert s.plan_chunks(2, (4, 4)) == \
        s.plan_speculative(2, (), (4, 4))[1]


# --- deprecated facades (satellite: one queue, one batcher) ------------------


def test_admission_queue_warns_but_behaves():
    with pytest.warns(DeprecationWarning, match="AdmissionQueue"):
        q = AdmissionQueue(max_depth=4)
    assert isinstance(q, ContinuousBatchScheduler)
    q.offer("a", cost=2)
    q.offer("b", cost=2)
    assert q.depth == 2
    admitted, shed = q.take(budget=8, slots=2)
    assert admitted == ["a", "b"] and shed == []
    assert q.depth == 0


def test_token_budget_batcher_warns_but_behaves():
    with pytest.warns(DeprecationWarning, match="TokenBudgetBatcher"):
        b = TokenBudgetBatcher(
            lambda batch: [{"ok": True} for _ in batch],
            token_budget=64, cost_fn=lambda p: len(p["x"]),
            max_delay_ms=1.0)
    try:
        futures = [b.submit({"x": "y" * 8}) for _ in range(4)]
        assert all(f.result(timeout=10)["ok"] for f in futures)
    finally:
        b.close()


def test_construction_is_the_only_warning_site():
    """The unified scheduler itself must stay warning-free — the
    facades warn, the replacement doesn't."""
    with warnings.catch_warnings():
        warnings.simplefilter("error", DeprecationWarning)
        s = ContinuousBatchScheduler(token_budget=8, max_chunk=4)
        s.plan_speculative(1, (2,), (4,))
