#!/usr/bin/env python
"""Masked-language-model pretraining CLI (reference ``scripts/mlm.py``).

Example (mirrors README.md:34-44):

    python scripts/mlm.py fit \\
      --data=IMDBDataModule --data.max_seq_len=512 --data.batch_size=64 \\
      --optimizer.init_args.lr=0.002 --trainer.max_steps=50000 \\
      --experiment=mlm
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from perceiver_tpu.data import IMDBDataModule  # noqa: E402
from perceiver_tpu.tasks import MaskedLanguageModelTask  # noqa: E402
from perceiver_tpu.utils.config import CLI, Link  # noqa: E402

TRAINER_YAML = os.path.join(os.path.dirname(__file__), "trainer.yaml")

# reference mlm.py:19-29 default masked samples
DEFAULT_MASKED_SAMPLES = [
    "I have watched this <MASK> and it was awesome",
    "I have <MASK> this movie and <MASK> did not like it",
]


def _is_onecycle(config: dict) -> bool:
    sched = config.get("lr_scheduler")
    return (isinstance(sched, dict)
            and str(sched.get("class_path", "")).rsplit(".", 1)[-1]
            == "OneCycleLR")


def main(args=None, run=True):
    return CLI(
        MaskedLanguageModelTask,
        datamodules={"IMDBDataModule": IMDBDataModule},
        default_datamodule="IMDBDataModule",
        default_config_files=[TRAINER_YAML],
        defaults={
            "experiment": "mlm",
            "model.masked_samples": DEFAULT_MASKED_SAMPLES,
            "model.num_predictions": 3,
            # the reference MLM CLI always trains under OneCycleLR
            # (mlm.py:14-16 registers it unconditionally); the links
            # below fill total_steps/max_lr. "defaulted" lets optim
            # fall back to constant lr when max_steps is unset, where
            # the reference would crash.
            "lr_scheduler.class_path": "OneCycleLR",
            "lr_scheduler.defaulted": True,
        },
        links=[
            # reference mlm.py:14-18: OneCycle total_steps ← max_steps,
            # max_lr ← optimizer lr; model vocab/seq ← datamodule.
            # Gated on the scheduler actually being OneCycleLR — the
            # user may switch class, and these args are OneCycle's
            Link("trainer.max_steps",
                 "lr_scheduler.init_args.total_steps",
                 when=_is_onecycle),
            Link("optimizer.init_args.lr", "lr_scheduler.init_args.max_lr",
                 when=_is_onecycle),
            Link("data.vocab_size", "model.vocab_size",
                 apply_on="instantiate"),
            Link("data.max_seq_len", "model.max_seq_len",
                 apply_on="instantiate"),
        ],
        description=__doc__,
        run=run,
        args=args,
    )


if __name__ == "__main__":
    main()
