"""IMDB data module with on-the-fly WordPiece tokenizer training.

Parity target: reference ``data/imdb.py``:

- ``prepare_data``: obtain the corpus, then train a WordPiece tokenizer
  (vocab 10003) on the training split and cache it as
  ``.cache/imdb-tokenizer-{vocab}.json`` (``imdb.py:96-103``).
- ``setup``: load tokenizer, build a ``Collator``, read raw datasets
  from ``aclImdb/{train,test}/{neg,pos}/*.txt`` (``imdb.py:24-38``).
- Batches: ``(label, token_ids, pad_mask)`` with ``pad_mask = ids ==
  pad_id`` True at padding (``imdb.py:59-64``).

TPU deviations (deliberate):

- The collator pads every batch to ``max_seq_len`` rather than to the
  longest sequence in the batch — ragged widths would recompile the
  jitted step per batch shape; one static width keeps a single XLA
  executable.
- Zero-egress environments get a deterministic synthetic review corpus
  (template sentences over polarity word pools) so the full pipeline —
  tokenizer training included — still runs end-to-end.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import uuid
from typing import List, Optional, Tuple

import numpy as np

from perceiver_tpu.data.core import ArrayDataset, BatchIterator
from perceiver_tpu.tokenizer import (
    PAD_TOKEN_ID,
    WordPieceTokenizer,
    create_tokenizer,
    load_tokenizer,
    save_tokenizer,
    train_tokenizer,
)
from perceiver_tpu.tokenizer.wordpiece import Replace


def _file_sha1(path: str) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _corpus_fingerprint(root: str) -> str:
    """Cheap content proxy for the aclImdb tree: doc count + total
    bytes per split/label dir (one stat scan, ~1 s for 100k docs —
    hashing the 36+ MB of text every setup() would not be). Detects
    in-place corpus rewrites that leave the tokenizer json untouched."""
    parts = []
    for split in ("train", "test"):
        for label in ("neg", "pos"):
            n = total = 0
            try:
                with os.scandir(os.path.join(root, split, label)) as it:
                    for e in it:
                        n += 1
                        total += e.stat().st_size
            except OSError:
                pass
            parts.append(f"{n}.{total}")
    return ":".join(parts)


class Collator:
    """Tokenize + truncate + fixed-width pad (reference imdb.py:52-68)."""

    def __init__(self, tokenizer: WordPieceTokenizer, max_seq_len: int):
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        tokenizer.enable_truncation(max_seq_len)

    def collate(self, labels, texts: List[str]):
        # one GIL-free native call tokenizes the whole batch across
        # C++ threads (padded-matrix batch API)
        ids, _ = self.tokenizer.encode_batch_padded(
            texts, self.max_seq_len, pad_id=PAD_TOKEN_ID)
        pad_mask = ids == PAD_TOKEN_ID
        return np.asarray(labels, np.int32), ids, pad_mask

    def encode(self, texts: List[str]):
        """Raw strings → (ids, pad_mask); reference imdb.py:66-68."""
        _, ids, pad_mask = self.collate([0] * len(texts), texts)
        return ids, pad_mask


_POS = ("wonderful great excellent brilliant moving superb delightful "
        "masterful charming touching gripping hilarious stunning").split()
_NEG = ("terrible awful boring dreadful laughable tedious bland "
        "disappointing forgettable incoherent clumsy lifeless dire").split()
_TEMPLATES = [
    "this movie was absolutely {w} and i {v} every minute of it",
    "a truly {w} film with {w2} acting and a {w3} script",
    "the director delivered a {w} story<br />the cast was {w2}",
    "i found the plot {w} but the ending was {w2}",
    "{w} cinematography, {w2} pacing, overall a {w3} experience",
]


def _synthetic_reviews(n: int, seed: int) -> Tuple[List[str], List[int]]:
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        label = int(rng.integers(0, 2))
        pool = _POS if label else _NEG
        tpl = _TEMPLATES[rng.integers(0, len(_TEMPLATES))]
        words = {
            "w": pool[rng.integers(0, len(pool))],
            "w2": pool[rng.integers(0, len(pool))],
            "w3": pool[rng.integers(0, len(pool))],
            "v": "loved" if label else "hated",
        }
        texts.append(tpl.format(**{k: v for k, v in words.items()
                                   if "{" + k + "}" in tpl}))
        labels.append(label)
    return texts, labels


def load_split(root: str, split: str) -> Tuple[List[str], List[int]]:
    """Read aclImdb/{split}/{neg,pos}/*.txt (reference imdb.py:24-38)."""
    texts, labels = [], []
    for label, sub in enumerate(("neg", "pos")):
        d = os.path.join(root, split, sub)
        for name in sorted(os.listdir(d)):
            if name.endswith(".txt"):
                with open(os.path.join(d, name), encoding="utf-8") as f:
                    texts.append(f.read())
                labels.append(label)
    return texts, labels


class IMDBDataModule:
    def __init__(self, data_dir: str = ".cache", vocab_size: int = 10003,
                 max_seq_len: int = 512, batch_size: int = 64,
                 shuffle: bool = True, seed: int = 0,
                 synthetic_train_size: int = 512,
                 synthetic_test_size: int = 128):
        self.data_dir = data_dir
        self.vocab_size = vocab_size
        self.max_seq_len = max_seq_len
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.synthetic_train_size = synthetic_train_size
        self.synthetic_test_size = synthetic_test_size
        self.tokenizer: Optional[WordPieceTokenizer] = None
        self.collator: Optional[Collator] = None
        self._train = self._test = None
        self.synthetic = False

    @property
    def aclimdb_root(self) -> str:
        return os.path.join(self.data_dir, "aclImdb")

    def _tokenizer_path_for(self, have_corpus: bool) -> str:
        # a tokenizer trained on the synthetic fallback corpus must
        # never be silently reused for the real one (its vocab would
        # map real reviews to [UNK]) — the cache name records which
        # corpus it was trained on
        tag = "" if have_corpus else "synthetic-"
        return os.path.join(
            self.data_dir, f"imdb-tokenizer-{tag}{self.vocab_size}.json")

    @property
    def tokenizer_path(self) -> str:
        return self._tokenizer_path_for(os.path.isdir(self.aclimdb_root))

    def _raw_train(self, have_corpus: Optional[bool] = None
                   ) -> Tuple[List[str], List[int]]:
        if have_corpus is None:
            have_corpus = os.path.isdir(self.aclimdb_root)
        if have_corpus:
            return load_split(self.aclimdb_root, "train")
        self.synthetic = True
        return _synthetic_reviews(self.synthetic_train_size, self.seed)

    def _raw_test(self, have_corpus: Optional[bool] = None
                  ) -> Tuple[List[str], List[int]]:
        if have_corpus is None:
            have_corpus = os.path.isdir(self.aclimdb_root)
        if have_corpus:
            return load_split(self.aclimdb_root, "test")
        self.synthetic = True
        return _synthetic_reviews(self.synthetic_test_size, self.seed + 1)

    _URL = "https://ai.stanford.edu/~amaas/data/sentiment/aclImdb_v1.tar.gz"

    def prepare_data(self):
        """Download the corpus if absent (imdb.py:92-94), then train +
        cache the tokenizer if absent (imdb.py:96-103). Both steps are
        best-effort offline: no corpus → synthetic reviews."""
        os.makedirs(self.data_dir, exist_ok=True)
        if not os.path.isdir(self.aclimdb_root):
            from perceiver_tpu.data.download import extract_tgz, fetch
            tgz = os.path.join(self.data_dir, "aclImdb_v1.tar.gz")
            if os.path.exists(tgz) or fetch(self._URL, tgz):
                # extract to a per-process temp dir and publish
                # atomically — a partial tree must never masquerade as
                # the corpus, and concurrent extractors never collide
                tmp = f"{self.aclimdb_root}.extract-tmp.{os.getpid()}"
                shutil.rmtree(tmp, ignore_errors=True)
                ok = extract_tgz(tgz, tmp) and \
                    os.path.isdir(os.path.join(tmp, "aclImdb"))
                if ok and not os.path.isdir(self.aclimdb_root):
                    try:
                        os.replace(os.path.join(tmp, "aclImdb"),
                                   self.aclimdb_root)
                    except OSError:
                        shutil.rmtree(tmp, ignore_errors=True)
                        if not os.path.isdir(self.aclimdb_root):
                            # not a lost race — the corpus was never
                            # published (permissions, read-only fs);
                            # surface it instead of silently training
                            # on synthetic data
                            raise
                shutil.rmtree(tmp, ignore_errors=True)
                if not ok:
                    # a tarball that extracts but has no aclImdb/ root
                    # (or fails) must not short-circuit future fetches
                    try:
                        os.unlink(tgz)
                    except OSError:
                        pass
        # snapshot corpus presence ONCE: the corpus choice, the cache
        # name, and the training text source must agree even if a
        # concurrent extractor publishes the real corpus mid-function
        have_corpus = os.path.isdir(self.aclimdb_root)
        tok_path = self._tokenizer_path_for(have_corpus)
        if os.path.exists(tok_path):
            return
        if have_corpus:
            texts, _ = load_split(self.aclimdb_root, "train")
        else:
            self.synthetic = True
            texts, _ = _synthetic_reviews(self.synthetic_train_size,
                                          self.seed)
        tokenizer = create_tokenizer(Replace("<br />", " "))
        train_tokenizer(tokenizer, texts, vocab_size=self.vocab_size)
        save_tokenizer(tokenizer, tok_path)

    def setup(self, stage: Optional[str] = None):
        if self._train is not None:
            return
        # snapshot corpus presence ONCE: the tokenizer cache name and
        # the text source must describe the same corpus even if a
        # concurrent extractor publishes aclImdb/ mid-setup
        have_corpus = os.path.isdir(self.aclimdb_root)
        tok_path = self._tokenizer_path_for(have_corpus)
        if not os.path.exists(tok_path):
            # standalone use (no Trainer): make setup self-sufficient —
            # but ONLY when the tokenizer cache is missing, so
            # multi-host runs (Trainer gates downloads to process 0)
            # never re-enter the download path from every process.
            # Corpus upgrades (offline run cached synthetic, network
            # returned) happen through prepare_data, which every
            # Trainer fit invokes and which re-attempts the download
            # whenever the real corpus is absent.
            self.prepare_data()
            # prepare_data may have just downloaded the real corpus —
            # re-snapshot so we train/load against what now exists
            have_corpus = os.path.isdir(self.aclimdb_root)
            tok_path = self._tokenizer_path_for(have_corpus)
        self.tokenizer = load_tokenizer(tok_path)
        self.collator = Collator(self.tokenizer, self.max_seq_len)

        # tokenized-array cache: re-tokenizing the full corpus costs
        # minutes of single-core host time per process start (paid on
        # every resume of a long run); the arrays are cheap to store.
        # Keyed by the tokenizer file's digest + seq_len + a corpus
        # fingerprint: the tokenizer digest alone misses an in-place
        # corpus rewrite (harvest_text.py regenerates .cache/aclImdb
        # without touching the tokenizer json — ADVICE r2), which would
        # silently serve stale ids AND stale labels.
        cache = (tok_path.replace(".json", f"-ids-L{self.max_seq_len}.npz")
                 if have_corpus else None)
        tok_sha = _file_sha1(tok_path) if cache else None
        corpus_fp = _corpus_fingerprint(self.aclimdb_root) if cache else None
        if cache and os.path.exists(cache):
            try:
                with np.load(cache, allow_pickle=False) as z:
                    if (str(z["tokenizer_sha"]) == tok_sha
                            and str(z.get("corpus_fp", "")) == corpus_fp):
                        self._train = ArrayDataset(
                            label=z["tr_y"], input_ids=z["tr_ids"],
                            pad_mask=z["tr_pad"])
                        self._test = ArrayDataset(
                            label=z["te_y"], input_ids=z["te_ids"],
                            pad_mask=z["te_pad"])
                        return
            except Exception:  # noqa: BLE001 — fall through and rebuild
                pass

        tr_texts, tr_labels = self._raw_train(have_corpus)
        te_texts, te_labels = self._raw_test(have_corpus)
        y, ids, pad = self.collator.collate(tr_labels, tr_texts)
        self._train = ArrayDataset(label=y, input_ids=ids, pad_mask=pad)
        y, ids, pad = self.collator.collate(te_labels, te_texts)
        self._test = ArrayDataset(label=y, input_ids=ids, pad_mask=pad)
        if cache:
            # atomic publish; the temp name must be unique across
            # processes AND hosts (containerized hosts sharing a cache
            # filesystem can collide on pid alone)
            tmp = f"{cache}.{uuid.uuid4().hex}.tmp.npz"
            tr, te = self._train.fields, self._test.fields
            np.savez(tmp, tokenizer_sha=tok_sha, corpus_fp=corpus_fp,
                     tr_y=tr["label"], tr_ids=tr["input_ids"],
                     tr_pad=tr["pad_mask"],
                     te_y=te["label"], te_ids=te["input_ids"],
                     te_pad=te["pad_mask"])
            os.replace(tmp, cache)

    def train_dataloader(self) -> BatchIterator:
        self.setup()
        return BatchIterator(self._train, self.batch_size,
                             shuffle=self.shuffle, seed=self.seed,
                             drop_last=True)

    def val_dataloader(self) -> BatchIterator:
        self.setup()
        return BatchIterator(self._test, self.batch_size)

    def test_dataloader(self) -> BatchIterator:
        self.setup()
        return BatchIterator(self._test, self.batch_size)
