"""perceiver_tpu — a TPU-native Perceiver / Perceiver IO framework.

Built from scratch on JAX/XLA: pure-function modules over parameter
pytrees, einsum attention lowered onto the MXU, pjit/GSPMD meshes for
distribution, and Pallas kernels for the attention hot loop.

Provides the full capability surface of the reference PyTorch
implementation (``felixyu7/perceiver-io-1``, see SURVEY.md): generic
``PerceiverEncoder``/``PerceiverDecoder``/``PerceiverIO`` models with
pluggable input/output adapters, BERT-style masked language modeling,
transfer learning with encoder freezing, image classification, and a
large-scale semantic-segmentation configuration.
"""

__version__ = "0.1.0"

from perceiver_tpu.models.perceiver import (  # noqa: F401
    PerceiverEncoder,
    PerceiverDecoder,
    PerceiverIO,
    PerceiverMLM,
)
