"""The shared online-softmax body of the Pallas attention kernels.

Flash attention (``pallas_attention``), ragged cross-attention
(``ragged_attention``), and paged decode attention
(``paged_attention``) all walk the kv axis block by block and carry
the same three VMEM accumulators: the running row max ``m``, the
running normalizer ``l``, and the unnormalized output accumulator
``acc`` (all fp32; m/l are stored lane-broadcast as ``(rows, 128)``
so the scratch tiles stay hardware-shaped). The rescale-and-
accumulate recurrence is identical across the three kv layouts —
only the score masking differs per kernel — so it lives here once
and each kernel supplies its own masked score block.

These helpers trace inside Pallas kernel bodies: arguments are
kernel refs, not arrays, and every statement must stay Mosaic-legal
(2D iota, lane-broadcast stats, ``preferred_element_type`` on dots).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from perceiver_tpu.ops.chunked_attention import NEG_INF

__all__ = [
    "online_softmax_init",
    "online_softmax_update",
    "online_softmax_finish",
]


def online_softmax_init(m_ref, l_ref, acc_ref) -> None:
    """Reset the accumulators at the first kv block (``j == 0``)."""
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)


def online_softmax_update(s, vblk, m_ref, l_ref, acc_ref) -> None:
    """One kv-block step: fold the masked fp32 score block ``s``
    (rows = queries, cols = this block's kv positions) and its value
    block ``vblk`` into the running (m, l, acc) state. Fully-masked
    columns must carry ``NEG_INF`` in ``s`` — they then contribute
    ``exp(NEG_INF - m) == 0`` to both ``l`` and ``acc``."""
    m_prev = m_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def online_softmax_finish(m_ref, l_ref, acc_ref):
    """Normalize the accumulator at the last kv block. Rows that saw
    only masked columns have ``l == 0`` and normalize to exact zeros
    (the ragged/paged kernels rely on this for empty requests)."""
    return acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
