"""Health-routed load balancer over fleet replicas.

The router is the fleet's single client-facing entry: ``submit``
picks a replica using its *health signals* (the replica's exported
health state, its current in-flight count, and a per-replica circuit
breaker owned by the router), dispatches, and transparently retries
recoverable failures on a sibling — so the caller's contract stays
the single-engine contract: a result, or a typed ``ServingError``.
Never a hang, never a dropped request (chaos-gated by
``scripts/chaos.py --fleet``).

Routing policy (docs/SERVING.md "Fleet"):

- candidates are replicas that are not draining, whose router-side
  breaker ``allow()``s traffic, and that were not already tried for
  this request;
- READY replicas are preferred over DEGRADED ones (a DEGRADED replica
  serves, but only when nothing healthier is idle); ties break to the
  lowest in-flight count (least-loaded);
- transport failures (``RpcError``: connection refused/reset, recv
  deadline on a stalled replica) record a breaker failure — repeated
  failures **eject** the replica (breaker OPEN) until a half-open
  probe (the background prober, or a later submit) readmits it;
- typed ``Unavailable`` from a replica (mid-swap ``updating``, open
  bucket breaker) excludes the replica for this request and retries a
  sibling without ejecting anyone;
- ``RequestTooLarge`` is deterministic — re-raised immediately, never
  retried;
- only when no candidate remains (every replica draining, ejected, or
  already tried) does the caller see ``Unavailable("fleet_saturated")``
  with a ``retry_after_s`` hint derived from the soonest breaker
  reopen.

Multi-tenancy (docs/SERVING.md "Multi-tenancy"): when constructed
with a :class:`~perceiver_tpu.serving.tenancy.TenantRegistry`, every
``submit`` is admission-checked against the caller's tenant *before
any replica is picked*: an exhausted in-flight cap or rate bucket
raises ``Unavailable("tenant_quota")`` with a ``retry_after_s`` hint,
costing zero compute and zero replica load. Best-effort tenants
(``priority >= PRIORITY_BEST_EFFORT``) get fewer retry attempts, so
under saturation their retries never crowd out critical tenants'.
Requests routed for a named model only consider replicas advertising
that model (replicas report ``models`` in status/dispatch replies);
tenancy is host-side state only — the compiled executables and the
RPC wire shape are tenant-blind.

Idempotency note: a retry after a transport error can re-execute a
dispatch whose first attempt actually completed server-side. Fleet
dispatch is pure inference (no server-side state mutation), so
at-least-once execution is safe and exactly-once *delivery* is what
the router guarantees.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Callable, Dict, List, Optional

from perceiver_tpu.fleet.rpc import RpcError
from perceiver_tpu.obs import events as events_mod
from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.resilience.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
)
from perceiver_tpu.serving.errors import Unavailable
from perceiver_tpu.serving.metrics import MetricsRegistry
from perceiver_tpu.serving.tenancy import (
    DEFAULT_TENANT,
    PRIORITY_BEST_EFFORT,
    TenantRegistry,
)

_HEALTH_RANK = {"READY": 0, "DEGRADED": 1, "STARTING": 2,
                "UNAVAILABLE": 3}

_BREAKER_STATE_VALUES = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


def _accepts_trace(handle) -> bool:
    """Does ``handle.dispatch`` take a ``trace`` kwarg?  Sniffed once
    at ``add()`` so plain fakes with ``dispatch(arrays)`` keep working
    and the hot path never inspects signatures."""
    try:
        sig = inspect.signature(handle.dispatch)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.name == "trace" or p.kind is inspect.Parameter.VAR_KEYWORD:
            return True
    return False


class _ReplicaState:
    """Router-side book-keeping for one replica."""

    def __init__(self, rid: str, handle, breaker: CircuitBreaker):
        self.rid = rid
        self.handle = handle
        self.breaker = breaker
        self.inflight = 0
        self.draining = False
        self.health = "READY"
        # None = "models unknown": the replica never advertised a model
        # list, so it is assumed to serve everything (single-model
        # fleets and plain fakes never pay the tenancy tax)
        self.models: Optional[frozenset] = None
        self.accepts_trace = _accepts_trace(handle)


class Router:
    """Load-balance ``submit`` calls over replica handles.

    A *handle* needs ``dispatch(arrays) -> {"outputs", "health", ...}``
    and ``status() -> dict`` (see :class:`fleet.supervisor.
    RpcReplicaHandle`); tests pass fakes.
    """

    # lock discipline (gated by check.py --race): the replica map and
    # every mutable _ReplicaState field the router itself writes
    # (any-receiver keys — the states are picked out of the map and
    # mutated through locals). rid/handle/breaker/accepts_trace are
    # write-once at add(); the breaker has its own internal lock.
    _GUARDED = {
        "_replicas": "_lock",
        "*.inflight": "_lock",
        "*.draining": "_lock",
        "*.health": "_lock",
        "*.models": "_lock",
        "_tenant_inflight": "_lock",
    }

    def __init__(self, *, max_attempts: int = 4,
                 retry_backoff_s: float = 0.02,
                 breaker_failure_threshold: int = 3,
                 breaker_reset_s: float = 1.0,
                 prober_interval_s: Optional[float] = 0.25,
                 metrics: Optional[MetricsRegistry] = None,
                 tenancy: Optional[TenantRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.retry_backoff_s = retry_backoff_s
        self._breaker_failure_threshold = breaker_failure_threshold
        self._breaker_reset_s = breaker_reset_s
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {}
        self.tenancy = tenancy
        self._tenant_inflight: Dict[str, int] = {}
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        m = self.metrics
        self._m_requests = m.counter(
            "fleet_requests_total",
            "router submits, by outcome (ok|unavailable|error)")
        self._m_retries = m.counter(
            "fleet_retries_total",
            "dispatch attempts retried on a sibling, by cause")
        self._m_size = m.gauge("fleet_size", "replicas known to the router")
        self._m_ejected = m.counter(
            "fleet_ejections_total",
            "replica ejections (router breaker opened)")
        self._m_readmitted = m.counter(
            "fleet_readmissions_total",
            "ejected replicas readmitted (router breaker re-closed)")
        self._m_inflight = m.gauge(
            "fleet_replica_inflight", "router-side in-flight per replica")
        self._m_breaker_state = m.gauge(
            "fleet_breaker_state",
            "per-replica router breaker: 0=closed 1=half_open 2=open")
        self._m_tenant_requests = m.counter(
            "fleet_tenant_requests_total",
            "router submits per tenant, by outcome "
            "(ok|unavailable|error|shed)")
        self._closed = threading.Event()
        self._prober: Optional[threading.Thread] = None
        if prober_interval_s:
            self._prober = threading.Thread(
                target=self._probe_loop, args=(prober_interval_s,),
                name="fleet-prober", daemon=True)
            self._prober.start()

    # -- membership -------------------------------------------------------

    def add(self, rid: str, handle) -> None:
        breaker = CircuitBreaker(
            failure_threshold=self._breaker_failure_threshold,
            reset_timeout_s=self._breaker_reset_s,
            clock=self._clock,
            on_transition=lambda old, new, _rid=rid:
                self._on_transition(_rid, old, new))
        with self._lock:
            self._replicas[rid] = _ReplicaState(rid, handle, breaker)
            self._m_size.set(len(self._replicas))
        self._m_breaker_state.labels(replica=rid).set(  # graphcheck: ignore — per-replica breaker gauge; tenant split is fleet_tenant_requests_total
            _BREAKER_STATE_VALUES[breaker.state])

    def _on_transition(self, rid: str, old: str, new: str) -> None:
        self._m_breaker_state.labels(replica=rid).set(  # graphcheck: ignore — per-replica breaker gauge; tenant split is fleet_tenant_requests_total
            _BREAKER_STATE_VALUES.get(new, 0.0))
        if new == OPEN:
            self._m_ejected.inc()
            events_mod.emit("fleet_ejection", replica=rid)  # graphcheck: ignore — fleet_ejection is replica-scoped (breaker state, not traffic)
        elif new == CLOSED and old != CLOSED:
            self._m_readmitted.inc()
            events_mod.emit("fleet_readmission", replica=rid)  # graphcheck: ignore — fleet_readmission is replica-scoped (breaker state, not traffic)

    def remove(self, rid: str) -> None:
        with self._lock:
            self._replicas.pop(rid, None)
            self._m_size.set(len(self._replicas))
        self._m_inflight.labels(replica=rid).remove()  # graphcheck: ignore — per-replica gauge removal on membership change
        self._m_breaker_state.labels(replica=rid).remove()  # graphcheck: ignore — per-replica gauge removal on membership change

    def replicas(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    def drain(self, rid: str) -> None:
        """Stop routing new requests to ``rid`` (existing in-flight
        requests finish normally)."""
        with self._lock:
            if rid in self._replicas:
                self._replicas[rid].draining = True

    def undrain(self, rid: str) -> None:
        with self._lock:
            if rid in self._replicas:
                self._replicas[rid].draining = False

    def wait_idle(self, rid: str, timeout: float = 10.0) -> bool:
        """Block until the router has no in-flight request on ``rid``
        (drain first, or this may never converge)."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            with self._lock:
                state = self._replicas.get(rid)
                if state is None or state.inflight == 0:
                    return True
            self._sleep(0.01)
        return False

    # -- routing ----------------------------------------------------------

    def _pick(self, exclude,
              model: Optional[str] = None) -> Optional[_ReplicaState]:
        key = lambda r: (_HEALTH_RANK.get(r.health, 3),  # noqa: E731
                         r.inflight, r.rid)
        with self._lock:
            avail = [r for r in self._replicas.values()
                     if r.rid not in exclude and not r.draining
                     and _HEALTH_RANK.get(r.health, 3) <= 1
                     and (model is None or r.models is None
                          or model in r.models)]
            pool = [r for r in avail if r.breaker.state == CLOSED]
            best = min(pool, key=key) if pool else None
            if best is None:
                # no healthy replica: offer ONE ejected replica its
                # half-open probe (allow() consumes the probe token,
                # so only call it on the replica actually dispatched)
                for r in sorted(avail, key=key):
                    if r.breaker.allow():
                        best = r
                        break
            if best is None:
                return None
            best.inflight += 1
            self._m_inflight.labels(replica=best.rid).set(best.inflight)  # graphcheck: ignore — per-replica inflight gauge; per-tenant demand is tenant_demand()
            return best

    def _release(self, state: _ReplicaState) -> None:
        with self._lock:
            state.inflight = max(0, state.inflight - 1)
            self._m_inflight.labels(replica=state.rid).set(state.inflight)  # graphcheck: ignore — per-replica inflight gauge; per-tenant demand is tenant_demand()

    def _retry_after_hint(self) -> float:
        with self._lock:
            hints = [r.breaker.retry_after()
                     for r in self._replicas.values()]
        open_hints = [h for h in hints if h > 0]
        return min(open_hints) if open_hints else 0.1

    # -- tenancy -----------------------------------------------------------

    def _admit_tenant(self, tenant: str):
        """Quota-check ``tenant`` BEFORE any replica is touched.

        Raises ``Unavailable("tenant_quota")`` (with a retry hint) on
        an exhausted in-flight cap or rate bucket; returns the tenant's
        spec otherwise. Zero compute is spent on a shed request.
        """
        spec = self.tenancy.get(tenant)
        if spec.max_inflight is not None:
            with self._lock:
                held = self._tenant_inflight.get(tenant, 0)
            if held >= spec.max_inflight:
                self._shed_tenant(tenant, retry_after_s=None)
        ok, retry_after = self.tenancy.admit(tenant)
        if not ok:
            self._shed_tenant(tenant, retry_after_s=retry_after)
        return spec

    def _shed_tenant(self, tenant: str, *,
                     retry_after_s: Optional[float]) -> None:
        self._m_tenant_requests.labels(tenant=tenant,
                                       outcome="shed").inc()
        events_mod.emit("tenant_shed", tenant=tenant,
                        reason="tenant_quota")
        raise Unavailable("tenant_quota", retry_after_s=retry_after_s,
                          tenant=tenant)

    def tenant_demand(self) -> Dict[str, int]:
        """Current router-side in-flight per tenant — the autoscaler's
        per-tenant demand signal (tenants seen at least once persist
        with 0 so demand decay is observable)."""
        with self._lock:
            return dict(self._tenant_inflight)

    def submit(self, arrays: dict, *, tenant: Optional[str] = None,
               model: Optional[str] = None) -> dict:
        """Dispatch one request; returns the replica's materialized
        outputs dict. Raises only typed serving errors.

        Tracing: requests arriving through a batcher carry attached
        trace contexts; a bare ``submit`` starts its own.  The router
        records ``route``/``rpc_hop``/``retry`` spans, ships the wire
        envelope to trace-capable replicas, absorbs the replica-side
        spans from the reply, and stamps ``reply["trace_id"]`` — so a
        request killed mid-flight and retried on a sibling yields ONE
        trace with the failed hop and the retry visible.

        Tenancy: ``tenant`` names the caller (defaults to the shared
        ``default`` tenant); quota admission runs first and can raise
        ``Unavailable("tenant_quota")`` before any replica dispatch.
        ``model`` restricts routing to replicas advertising that model
        and is forwarded on the wire so multi-model replicas dispatch
        against the right param set.
        """
        tenant = tenant or DEFAULT_TENANT
        attempts = self.max_attempts
        if self.tenancy is not None:
            spec = self._admit_tenant(tenant)
            if model is None:
                model = spec.model
            if spec.priority >= PRIORITY_BEST_EFFORT:
                # best-effort retries must not crowd out critical
                # tenants' attempts when the pool is saturated
                attempts = max(1, self.max_attempts // 2)
        if tenant != DEFAULT_TENANT or model is not None:
            # stamp the wire envelope (shallow copy: caller's dict is
            # caller-owned); replicas route "model" to the matching
            # param set and label their shed/usage metrics by "tenant"
            arrays = dict(arrays)
            arrays["tenant"] = tenant
            if model is not None:
                arrays["model"] = model
        with self._lock:
            self._tenant_inflight[tenant] = (
                self._tenant_inflight.get(tenant, 0) + 1)
        try:
            return self._submit_routed(arrays, tenant=tenant,
                                       model=model, attempts=attempts)
        finally:
            with self._lock:
                held = self._tenant_inflight.get(tenant, 0)
                self._tenant_inflight[tenant] = max(0, held - 1)

    def _submit_routed(self, arrays: dict, *, tenant: str,
                       model: Optional[str], attempts: int) -> dict:
        ctxs = trace_mod.attached()
        if not ctxs:
            own = trace_mod.start_trace(origin="router")
            if own is not None:
                ctxs = (own,)
        wire = ctxs[0].wire() if ctxs else None
        exclude: set = set()
        last_unavailable: Optional[Unavailable] = None
        for attempt in range(attempts):
            pick_start = time.monotonic()
            state = self._pick(exclude, model)
            if state is None:
                if attempt + 1 >= attempts:
                    break
                # transient no-candidate (e.g. every replica tried once
                # while one was mid-swap): back off and retry the full
                # pool before declaring the fleet saturated
                self._sleep(self.retry_backoff_s * (attempt + 1))
                exclude.clear()
                continue
            for c in ctxs:
                c.record("route", start=pick_start, replica=state.rid,
                         attempt=attempt, tenant=tenant)
            hop_start = time.monotonic()
            try:
                if wire is not None and state.accepts_trace:
                    reply = state.handle.dispatch(arrays, trace=wire)
                else:
                    reply = state.handle.dispatch(arrays)
            except RpcError:
                self._release(state)
                state.breaker.record_failure()
                exclude.add(state.rid)
                self._m_retries.labels(cause="transport").inc()  # graphcheck: ignore — aggregate retry-cause series; retry trace spans carry tenant
                for c in ctxs:
                    c.record("rpc_hop", start=hop_start,
                             replica=state.rid, ok=False,
                             error="transport")
                retry_start = time.monotonic()
                self._sleep(self.retry_backoff_s * (attempt + 1))
                for c in ctxs:
                    c.record("retry", start=retry_start,
                             cause="transport", attempt=attempt,
                             tenant=tenant)
                continue
            except Unavailable as e:
                self._release(state)
                # replica-refused (mid-swap, open bucket breaker):
                # typed and immediate — try a sibling, no ejection
                last_unavailable = e
                exclude.add(state.rid)
                self._m_retries.labels(cause="unavailable").inc()  # graphcheck: ignore — aggregate retry-cause series; retry trace spans carry tenant
                for c in ctxs:
                    c.record("rpc_hop", start=hop_start,
                             replica=state.rid, ok=False,
                             error="unavailable")
                    c.record("retry", cause="unavailable",
                             attempt=attempt, tenant=tenant)
                continue
            except Exception:
                self._release(state)
                state.breaker.record_failure()
                self._m_requests.labels(outcome="error").inc()  # graphcheck: ignore — aggregate outcome series; tenant split is fleet_tenant_requests_total below
                self._m_tenant_requests.labels(
                    tenant=tenant, outcome="error").inc()
                raise
            self._release(state)
            state.breaker.record_success()
            for c in ctxs:
                c.record("rpc_hop", start=hop_start,
                         replica=state.rid, ok=True)
            if isinstance(reply, dict):
                spans = reply.pop("spans", None)
                if spans:
                    for c in ctxs:
                        c.absorb(spans, replica=state.rid)
                if ctxs:
                    reply.setdefault("trace_id", ctxs[0].trace_id)
                # under the lock: _pick reads health on another thread
                # concurrently, and a torn read there routes traffic to
                # a replica the reply just reported UNAVAILABLE
                with self._lock:
                    state.health = reply.get("health", state.health)
                    models = reply.get("models")
                    if models is not None:
                        state.models = frozenset(models)
            self._m_requests.labels(outcome="ok").inc()  # graphcheck: ignore — aggregate outcome series; tenant split is fleet_tenant_requests_total below
            self._m_tenant_requests.labels(tenant=tenant,
                                           outcome="ok").inc()
            return reply
        self._m_requests.labels(outcome="unavailable").inc()  # graphcheck: ignore — aggregate outcome series; tenant split is fleet_tenant_requests_total below
        self._m_tenant_requests.labels(tenant=tenant,
                                       outcome="unavailable").inc()
        retry_after = self._retry_after_hint()
        if last_unavailable is not None:
            retry_after = max(retry_after,
                              last_unavailable.retry_after_s)
        raise Unavailable("fleet_saturated", retry_after_s=retry_after,
                          tenant=tenant)

    def occupancy(self) -> float:
        """Mean router-side in-flight per routable replica — the
        autoscaler's input signal."""
        with self._lock:
            live = [r for r in self._replicas.values() if not r.draining]
            if not live:
                return 0.0
            return sum(r.inflight for r in live) / len(live)

    # -- background probing -----------------------------------------------

    def _probe_loop(self, interval: float) -> None:
        """Refresh replica health; record failures for unreachable
        replicas so ejection does not have to wait for live traffic.
        Deliberately never records *success*: a replica whose control
        plane answers can still have a stalled dispatch path, so
        readmission only happens through a successful real dispatch
        (the half-open traffic probe in ``_pick``)."""
        while not self._closed.wait(interval):
            with self._lock:
                states = list(self._replicas.values())
            for state in states:
                try:
                    status = state.handle.status()
                except (RpcError, Unavailable):
                    # probe failure feeds the breaker like traffic
                    # would, but costs no user request
                    if state.breaker.state == CLOSED:
                        state.breaker.record_failure()
                    continue
                except Exception:  # pragma: no cover - handle bug
                    continue  # graphcheck: ignore — prober must not die
                with self._lock:
                    state.health = status.get("health", state.health)
                    models = status.get("models")
                    if models is not None:
                        state.models = frozenset(models)

    def close(self) -> None:
        self._closed.set()
        if self._prober is not None:
            self._prober.join(2.0)
