"""Model-integrated shard_map attention impls vs the einsum baseline.

The encoder's cross-attention can run as a shard_map kernel over a
mesh ("seqpar"/"ring"/"ulysses"); the result must match the plain
einsum single-device computation — same params, same rng, same loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_tpu.parallel import make_mesh
from perceiver_tpu.tasks import MaskedLanguageModelTask
from perceiver_tpu.ops.policy import Policy

POLICY = Policy.fp32()


def _task(impl=None):
    return MaskedLanguageModelTask(
        vocab_size=96, max_seq_len=32, num_latents=8,
        num_latent_channels=16, num_encoder_layers=2,
        num_encoder_self_attention_layers_per_block=2,
        num_encoder_cross_attention_heads=2,
        num_encoder_self_attention_heads=2,
        num_decoder_cross_attention_heads=2,
        attention_impl=impl, loss_impl="dense")


def _batch(b=4, l=32):
    rng = np.random.default_rng(0)
    return {
        "input_ids": jnp.asarray(rng.integers(3, 96, (b, l)), jnp.int32),
        "pad_mask": jnp.asarray(rng.random((b, l)) < 0.2),
    }


def _loss(task, model, batch):
    params = model.init(jax.random.key(0))
    loss, _ = task.loss_and_metrics(model, params, batch,
                                    rng=jax.random.key(7),
                                    deterministic=True, policy=POLICY)
    return float(loss)


@pytest.mark.parametrize("impl,seq_parallel", [
    ("seqpar", 4),
    ("ring", 4),
    # ulysses re-shards heads over the seq axis, so the axis size must
    # divide the 2 cross-attention heads
    ("ulysses", 2),
])
def test_matches_einsum_baseline(impl, seq_parallel):
    mesh = make_mesh(8, seq_parallel=seq_parallel, model_parallel=1)
    baseline = _loss(_task(), _task().build(), _batch())
    task = _task(impl)
    got = _loss(task, task.build(mesh=mesh), _batch())
    np.testing.assert_allclose(got, baseline, rtol=2e-5)


def test_spmd_impl_requires_seq_axis():
    task = _task("seqpar")
    with pytest.raises(ValueError, match="seq"):
        task.build()  # no mesh
    with pytest.raises(ValueError, match="seq"):
        task.build(mesh=make_mesh(8))  # mesh without a seq axis


def test_full_train_step_under_jit():
    """grad + AdamW through the shard_map path compiles and runs."""
    import optax

    mesh = make_mesh(8, seq_parallel=2, model_parallel=2)
    task = _task("seqpar")
    model = task.build(mesh=mesh)
    params = model.init(jax.random.key(0))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    batch = _batch()

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            loss, _ = task.loss_and_metrics(
                model, p, batch, rng=jax.random.key(3),
                deterministic=True, policy=POLICY)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    with mesh:
        _, _, loss = step(params, opt_state)
    assert np.isfinite(float(loss))
