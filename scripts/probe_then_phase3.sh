#!/bin/bash
# Probe the axon tunnel with a real matmul execution until it comes
# back, then run the phase-3 perf matrix. One probe every 2 min, same
# cadence the round-2..4 watcher used.
cd "$(dirname "$0")/.."
mkdir -p logs
while true; do
  if timeout 90 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((512,512), jnp.bfloat16)
(x@x).block_until_ready()" >/dev/null 2>&1; then
    echo "tunnel up at $(date -u +%H:%M:%S)" >> logs/probe_phase3.log
    bash scripts/perf_matrix_r05c.sh >> logs/perf_matrix_r05c.log 2>&1
    exit 0
  fi
  echo "probe failed at $(date -u +%H:%M:%S)" >> logs/probe_phase3.log
  sleep 120
done
