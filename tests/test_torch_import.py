"""Reference-checkpoint import: numerical equivalence vs torch.

The reference publishes trained Lightning checkpoints
(``/root/reference/README.md:72-74``); ``utils/torch_import`` converts
their state dicts into this framework's parameter pytree. These tests
prove the conversion is *numerically* faithful against the public
``torch.nn`` modules the reference composes (``nn.MultiheadAttention``
with packed and asymmetric projections, the LN→Linear→GELU→Linear MLP),
and that a full synthesized Lightning checkpoint round-trips into a
template pytree with exact structure/shape agreement.
"""

import dataclasses

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from perceiver_tpu.ops.attention import mha_apply  # noqa: E402
from perceiver_tpu.ops.mlp import mlp_apply  # noqa: E402
from perceiver_tpu.ops.policy import Policy  # noqa: E402
from perceiver_tpu.utils.torch_import import (  # noqa: E402
    _SD,
    _convert_mha,
    _convert_mlp,
    assert_tree_matches,
    convert_perceiver_params,
    load_lightning_state_dict,
    restore_from_torch,
)

def _policy():
    # exact fp32 compute for equivalence checks
    return Policy.fp32()


def _np(t):
    return t.detach().cpu().numpy()


def _tensors(sd):
    return {k: torch.as_tensor(v) for k, v in sd.items()}


@pytest.mark.parametrize("asymmetric", [False, True])
def test_mha_matches_torch(asymmetric):
    torch.manual_seed(0)
    d, h, kdim = 16, 4, (24 if asymmetric else 16)
    mha = torch.nn.MultiheadAttention(
        embed_dim=d, num_heads=h, kdim=kdim, vdim=kdim, batch_first=True)
    sd = {k: _np(v) for k, v in mha.state_dict().items()}
    if asymmetric:
        assert "q_proj_weight" in sd  # separate-projection layout
    else:
        assert "in_proj_weight" in sd  # packed layout
    params = _convert_mha(_SD(sd), "")

    b, lq, lk = 2, 5, 7
    q = torch.randn(b, lq, d)
    kv = torch.randn(b, lk, kdim)
    pad = torch.zeros(b, lk, dtype=torch.bool)
    pad[0, -2:] = True  # True = padding, same convention both sides
    want, _ = mha(q, kv, kv, key_padding_mask=pad)

    got = mha_apply(jax.tree.map(jnp.asarray, params),
                    jnp.asarray(_np(q)), jnp.asarray(_np(kv)),
                    jnp.asarray(_np(kv)), num_heads=h,
                    key_padding_mask=jnp.asarray(_np(pad)),
                    policy=_policy())
    np.testing.assert_allclose(np.asarray(got), _np(want),
                               rtol=1e-5, atol=1e-5)


def test_mlp_matches_torch():
    torch.manual_seed(1)
    d = 16
    ln = torch.nn.LayerNorm(d)
    fc1, fc2 = torch.nn.Linear(d, d), torch.nn.Linear(d, d)
    # reference mlp = Sequential(LN, Linear, GELU, Linear)
    # (model.py:20-26) → state-dict indices 0, 1, 3
    sd = {}
    for i, m in ((0, ln), (1, fc1), (3, fc2)):
        for k, v in m.state_dict().items():
            sd[f"{i}.{k}"] = _np(v)
    params = _convert_mlp(_SD(sd), "")

    x = torch.randn(2, 5, d)
    want = fc2(torch.nn.functional.gelu(fc1(ln(x))))
    got = mlp_apply(jax.tree.map(jnp.asarray, params),
                    jnp.asarray(_np(x)), policy=_policy())
    np.testing.assert_allclose(np.asarray(got), _np(want),
                               rtol=1e-5, atol=1e-5)


def _residual_cross_layer_sd(d, kdim, h, seed):
    """State dict of one reference cross_attention_layer
    (``model.py:29-33``): Residual(CrossAttention)+Residual(mlp),
    assembled from public torch modules with reference key names."""
    torch.manual_seed(seed)
    sd = {}
    qn, kn = torch.nn.LayerNorm(d), torch.nn.LayerNorm(kdim)
    mha = torch.nn.MultiheadAttention(embed_dim=d, num_heads=h,
                                      kdim=kdim, vdim=kdim,
                                      batch_first=True)
    for k, v in qn.state_dict().items():
        sd[f"0.module.q_norm.{k}"] = _np(v)
    for k, v in kn.state_dict().items():
        sd[f"0.module.kv_norm.{k}"] = _np(v)
    for k, v in mha.state_dict().items():
        sd[f"0.module.attention.attention.{k}"] = _np(v)
    ln = torch.nn.LayerNorm(d)
    fc1, fc2 = torch.nn.Linear(d, d), torch.nn.Linear(d, d)
    for i, m in ((0, ln), (1, fc1), (3, fc2)):
        for k, v in m.state_dict().items():
            sd[f"1.module.{i}.{k}"] = _np(v)
    modules = (qn, kn, mha, ln, fc1, fc2)
    return sd, modules


def _self_layer_sd(d, h, seed):
    """State dict of one reference self_attention_layer
    (``model.py:36-40``) with reference key names."""
    torch.manual_seed(seed)
    sd = {}
    n = torch.nn.LayerNorm(d)
    mha = torch.nn.MultiheadAttention(embed_dim=d, num_heads=h,
                                      batch_first=True)
    for k, v in n.state_dict().items():
        sd[f"0.module.norm.{k}"] = _np(v)
    for k, v in mha.state_dict().items():
        sd[f"0.module.attention.attention.{k}"] = _np(v)
    ln = torch.nn.LayerNorm(d)
    fc1, fc2 = torch.nn.Linear(d, d), torch.nn.Linear(d, d)
    for i, m in ((0, ln), (1, fc1), (3, fc2)):
        for k, v in m.state_dict().items():
            sd[f"1.module.{i}.{k}"] = _np(v)
    return sd


def _full_mlm_state_dict(v, l, n, d, c_in, h, n_self, n_layers):
    """A complete reference-MLM Lightning ``state_dict`` (prefix
    ``model.``) synthesized from public torch modules, with the exact
    key paths the reference module tree produces."""
    torch.manual_seed(42)
    sd = {}
    emb = torch.nn.Embedding(v, c_in)
    sd["model.encoder.input_adapter.text_embedding.weight"] = _np(emb.weight)
    sd["model.encoder.input_adapter.pos_encoding"] = _np(torch.randn(l, c_in))
    sd["model.encoder.latent"] = _np(torch.randn(n, d))
    layers = ["layer_1"] + (["layer_n"] if n_layers > 1 else [])
    for li, layer in enumerate(layers):
        cross_sd, _ = _residual_cross_layer_sd(d, c_in, h, 100 + li)
        for k, val in cross_sd.items():
            sd[f"model.encoder.{layer}.0.{k}"] = val
        for i in range(n_self):
            for k, val in _self_layer_sd(d, h, 200 + 10 * li + i).items():
                sd[f"model.encoder.{layer}.1.{i}.{k}"] = val
    sd["model.decoder.output"] = _np(torch.randn(l, d))
    dec_sd, _ = _residual_cross_layer_sd(d, d, h, 300)
    for k, val in dec_sd.items():
        sd[f"model.decoder.cross_attention.{k}"] = val
    out = torch.nn.Linear(d, v)
    sd["model.decoder.output_adapter.linear.weight"] = _np(out.weight)
    sd["model.decoder.output_adapter.linear.bias"] = _np(out.bias)
    return sd


def test_full_lightning_mlm_checkpoint_roundtrip(tmp_path):
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    v, l, n, d, h, n_self, n_layers = 50, 12, 8, 16, 4, 2, 3
    task = MaskedLanguageModelTask(
        vocab_size=v, max_seq_len=l, num_latents=n, num_latent_channels=d,
        num_encoder_layers=n_layers,
        num_encoder_cross_attention_heads=h,
        num_encoder_self_attention_heads=h,
        num_decoder_cross_attention_heads=h,
        num_encoder_self_attention_layers_per_block=n_self)
    model = task.build()
    template = model.init(jax.random.key(0))
    c_in = d  # text adapter embeds into num_latent_channels

    sd = _full_mlm_state_dict(v, l, n, d, c_in, h, n_self, n_layers)
    path = tmp_path / "reference_mlm.ckpt"
    torch.save({"state_dict": _tensors(sd), "hyper_parameters": {}},
               str(path))

    loaded = load_lightning_state_dict(str(path))
    params = convert_perceiver_params(loaded)
    assert_tree_matches(params, template)

    # the imported params must run through the real jitted model
    ids = jnp.asarray(np.random.default_rng(0).integers(3, v, (2, l)),
                      jnp.int32)
    pad = jnp.zeros((2, l), bool)
    logits, _ = model.apply(jax.tree.map(jnp.asarray, params), ids, pad,
                            masking=False, policy=_policy())
    assert logits.shape == (2, l, v)
    assert bool(jnp.isfinite(logits).all())

    # task-level flag drives the same import (trainer's
    # restore_pretrained hook)
    task2 = MaskedLanguageModelTask(
        vocab_size=v, max_seq_len=l, num_latents=n, num_latent_channels=d,
        num_encoder_layers=n_layers,
        num_encoder_cross_attention_heads=h,
        num_encoder_self_attention_heads=h,
        num_decoder_cross_attention_heads=h,
        num_encoder_self_attention_layers_per_block=n_self,
        torch_ckpt=str(path))
    restored = task2.restore_pretrained(template)
    chex_like = jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        restored, params)
    del chex_like


def test_mismatched_config_fails_loudly(tmp_path):
    sd = _full_mlm_state_dict(50, 12, 8, 16, 16, 4, 2, 3)
    path = tmp_path / "ckpt.pt"
    torch.save({"state_dict": _tensors(sd)}, str(path))
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    task = MaskedLanguageModelTask(
        vocab_size=50, max_seq_len=12, num_latents=4,  # wrong latents
        num_latent_channels=16, num_encoder_layers=3,
        num_encoder_cross_attention_heads=4,
        num_encoder_self_attention_heads=4,
        num_decoder_cross_attention_heads=4,
        num_encoder_self_attention_layers_per_block=2)
    template = task.build().init(jax.random.key(0))
    with pytest.raises(ValueError, match="shape"):
        restore_from_torch(str(path), template=template)


def test_encoder_transfer_into_classifier(tmp_path):
    """torch_mlm_ckpt: the reference two-phase recipe's encoder
    transfer (``lightning.py:144-146``) straight from a torch MLM
    checkpoint into the classifier task."""
    from perceiver_tpu.tasks import (
        MaskedLanguageModelTask,
        TextClassifierTask,
    )

    v, l, n, d, h, n_self, n_layers = 50, 12, 8, 16, 4, 2, 3
    sd = _full_mlm_state_dict(v, l, n, d, d, h, n_self, n_layers)
    path = tmp_path / "mlm.ckpt"
    torch.save({"state_dict": _tensors(sd)}, str(path))

    clf = TextClassifierTask(
        vocab_size=v, max_seq_len=l, num_classes=2, num_latents=n,
        num_latent_channels=d, num_encoder_layers=n_layers,
        num_encoder_cross_attention_heads=h,
        num_encoder_self_attention_heads=h,
        num_decoder_cross_attention_heads=1,
        num_encoder_self_attention_layers_per_block=n_self,
        torch_mlm_ckpt=str(path))
    template = clf.build().init(jax.random.key(0))
    restored = clf.restore_pretrained(template)
    # encoder subtree replaced by the torch weights...
    got_embed = np.asarray(restored["encoder"]["input_adapter"]["embed"])
    np.testing.assert_array_equal(
        got_embed, sd["model.encoder.input_adapter.text_embedding.weight"])
    # ...decoder untouched (classifier head is fresh)
    np.testing.assert_array_equal(
        np.asarray(restored["decoder"]["query"]),
        np.asarray(template["decoder"]["query"]))

def test_image_checkpoint_import(tmp_path):
    """Image-classifier import: the Fourier position buffer in the
    checkpoint is dropped (recomputed here), the empty input_adapter
    subtree still matches the framework template."""
    from perceiver_tpu.tasks import ImageClassifierTask

    shape, bands, n, d, h, n_self, n_layers = (8, 8, 1), 4, 8, 16, 4, 2, 2
    c_in = 2 * (2 * bands + 1) + shape[-1]  # adapter.py:96-97
    task = ImageClassifierTask(
        image_shape=shape, num_classes=5, num_frequency_bands=bands,
        num_latents=n, num_latent_channels=d, num_encoder_layers=n_layers,
        num_encoder_cross_attention_heads=h,
        num_encoder_self_attention_heads=h,
        num_decoder_cross_attention_heads=h,
        num_encoder_self_attention_layers_per_block=n_self)
    template = task.build().init(jax.random.key(0))

    torch.manual_seed(7)
    # REAL classifier layout: PerceiverIO subclasses nn.Sequential
    # (model.py:321-325), so encoder/decoder serialize as 0./1.
    sd = {"model.0.input_adapter.position_encoding":
          _np(torch.randn(shape[0], shape[1], c_in - shape[-1])),
          "model.0.latent": _np(torch.randn(n, d))}
    layers = ["layer_1"] + (["layer_n"] if n_layers > 1 else [])
    for li, layer in enumerate(layers):
        cross_sd, _ = _residual_cross_layer_sd(d, c_in, h, 400 + li)
        for k, val in cross_sd.items():
            sd[f"model.0.{layer}.0.{k}"] = val
        for i in range(n_self):
            for k, val in _self_layer_sd(d, h, 500 + 10 * li + i).items():
                sd[f"model.0.{layer}.1.{i}.{k}"] = val
    sd["model.1.output"] = _np(torch.randn(1, d))
    dec_sd, _ = _residual_cross_layer_sd(d, d, h, 600)
    for k, val in dec_sd.items():
        sd[f"model.1.cross_attention.{k}"] = val
    out = torch.nn.Linear(d, 5)
    sd["model.1.output_adapter.linear.weight"] = _np(out.weight)
    sd["model.1.output_adapter.linear.bias"] = _np(out.bias)

    path = tmp_path / "img.ckpt"
    torch.save({"state_dict": _tensors(sd)}, str(path))

    task2 = dataclasses.replace(task, torch_ckpt=str(path))
    restored = task2.restore_pretrained(template)
    assert_tree_matches(restored, template)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, *shape)),
                    jnp.float32)
    logits = task.build().apply(jax.tree.map(jnp.asarray, restored), x,
                                policy=_policy())
    assert logits.shape == (2, 5) and bool(jnp.isfinite(logits).all())


def test_runpy_style_prefix_autodetect(tmp_path):
    """run.py saves {'model_state_dict': ...} with keys under
    'perceiver.' (run.py:102,278-281) — prefix auto-detection finds
    them."""
    v, l, n, d, h, n_self, n_layers = 20, 6, 4, 16, 4, 2, 2
    sd = _full_mlm_state_dict(v, l, n, d, d, h, n_self, n_layers)
    def _seq(k):
        k = k[len("model."):]
        for name, idx in (("encoder.", "0."), ("decoder.", "1.")):
            if k.startswith(name):
                return "perceiver." + idx + k[len(name):]
        return "perceiver." + k
    runpy_sd = {_seq(k): torch.as_tensor(val) for k, val in sd.items()}
    path = tmp_path / "runpy.ckpt"
    torch.save({"epoch": 3, "model_state_dict": runpy_sd,
                "optimizer_state_dict": {}}, str(path))

    params = convert_perceiver_params(load_lightning_state_dict(str(path)))
    want = convert_perceiver_params(sd)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), params, want)


def test_export_roundtrip_and_torch_loadable():
    """Export (our pytree → reference state dict) round-trips through
    the importer bit-exactly, and the exported MHA slice strict-loads
    into a real ``nn.MultiheadAttention``."""
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    from perceiver_tpu.utils.torch_import import export_perceiver_params

    task = MaskedLanguageModelTask(
        vocab_size=30, max_seq_len=8, num_latents=4, num_latent_channels=16,
        num_encoder_layers=2, num_encoder_cross_attention_heads=4,
        num_encoder_self_attention_heads=4,
        num_decoder_cross_attention_heads=4,
        num_encoder_self_attention_layers_per_block=2)
    params = jax.tree.map(np.asarray, task.build().init(jax.random.key(3)))

    sd = export_perceiver_params(params)
    back = convert_perceiver_params(sd)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), back, params)

    # torch accepts the exported attention layout verbatim
    mha = torch.nn.MultiheadAttention(embed_dim=16, num_heads=4,
                                      batch_first=True)
    pre = "model.encoder.layer_1.0.0.module.attention.attention."
    slice_sd = {k[len(pre):]: torch.as_tensor(v) for k, v in sd.items()
                if k.startswith(pre)}
    mha.load_state_dict(slice_sd, strict=True)

    # sequential (classifier/run.py) child naming also round-trips
    seq_sd = export_perceiver_params(params, sequential=True)
    assert "model.0.latent" in seq_sd and "model.1.output" in seq_sd
    back_seq = convert_perceiver_params(seq_sd)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), back_seq, params)
