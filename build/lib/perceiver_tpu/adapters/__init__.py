"""Task-specific input/output adapters (reference ``perceiver/adapter.py``)."""

from perceiver_tpu.adapters.image import ImageInputAdapter  # noqa: F401
from perceiver_tpu.adapters.text import TextInputAdapter  # noqa: F401
from perceiver_tpu.adapters.output import (  # noqa: F401
    ClassificationOutputAdapter,
    SemanticSegOutputAdapter,
    TextOutputAdapter,
)
