"""Zero-downtime rolling param updates with auto-rollback.

The cutover rides two existing seams: the engine's recompile-free
``update_params`` (same shapes → same executables, so a version swap
costs a ``device_put``, not a compile) and the sha256-sealed
:class:`~perceiver_tpu.training.checkpoint.ParamsVersionStore` (a
replica refuses to load a version whose manifest check fails).

Per replica, in order (docs/SERVING.md "Fleet"):

1. ``router.drain(rid)`` — no new traffic routes to the replica;
2. ``router.wait_idle(rid)`` — router-side in-flight reaches zero;
3. ``handle.update_version(v)`` — the replica quiesces its own
   in-flight dispatches, verifies ``v``'s manifest, swaps params
   (requests racing the swap get a typed ``Unavailable("updating")``
   that the router retries on a sibling — no request is ever served
   mid-swap);
4. ``router.undrain(rid)`` — traffic returns, now on the new version.

Failure at any replica triggers **auto-rollback**: the failing replica
is undrained (it still serves the old version), every
already-updated replica is rolled back to the previous version through
the same drain/cutover steps, the store's CURRENT pointer is left
untouched, and a typed :class:`RolloutAborted` reports both the cause
and the rollback outcome. Mid-rollout checkpoint corruption is chaos-
gated (``scripts/chaos.py --fleet``, scenario ``fleet_rollout_corrupt``).

Process-group replicas (``distributed/serving_group.py``) plug in at
step 3 unchanged: the group handle's ``update_version`` IS the
two-phase stage-then-commit cutover, whose own member-level rollback
guarantees a group is never left torn; its typed
``GroupCutoverError`` lands in the same ``except`` below, so a member
killed between stage and swap rolls the whole FLEET back with CURRENT
untouched (chaos scenario ``dist_cutover_kill``).
"""

from __future__ import annotations

from typing import Callable, Optional

from perceiver_tpu.obs import events as events_mod


class RolloutAborted(RuntimeError):
    """The rolling update failed and was rolled back.

    ``cause`` is the replica-side failure; ``rolled_back`` lists the
    replicas restored to the previous version; ``rollback_failed``
    lists any that could not be restored (fleet left mixed — the
    supervisor's restart path will converge them)."""

    def __init__(self, message: str, cause: Exception,
                 rolled_back, rollback_failed):
        super().__init__(message)
        self.cause = cause
        self.rolled_back = list(rolled_back)
        self.rollback_failed = list(rollback_failed)


def _cutover(fleet, rid: str, version: str, *,
             drain_timeout_s: float,
             model: Optional[str] = None) -> None:
    """Steps 1-4 for one replica; raises on verification/swap failure
    with the replica undrained (it still serves its old version).

    ``model`` scopes the cutover to one param set on a multi-model
    replica — the "model" kwarg only goes on the wire when given, so
    legacy replicas and plain fake handles keep their single-model
    ``update_version(version)`` signature."""
    fleet.router.drain(rid)
    events_mod.emit("rollout_step", replica=rid, stage="drain",  # graphcheck: ignore — rollout_step is replica-scoped control plane; the per-tenant rollout carries model=, tenants unaffected by design
                    version=version)
    try:
        fleet.router.wait_idle(rid, timeout=drain_timeout_s)
        handle = fleet.supervisor.handle_of(rid)
        if handle is None:
            raise RuntimeError(f"replica {rid} vanished mid-rollout")
        if model is not None:
            handle.update_version(version, model=model)
        else:
            handle.update_version(version)
        events_mod.emit("rollout_step", replica=rid, stage="cutover",  # graphcheck: ignore — rollout_step is replica-scoped control plane
                        version=version)
    finally:
        fleet.router.undrain(rid)
        events_mod.emit("rollout_step", replica=rid, stage="undrain",  # graphcheck: ignore — rollout_step is replica-scoped control plane
                        version=version)


def _resolve_store(fleet, model: Optional[str]):
    """The version store a rollout verifies against: ``model`` picks
    the per-model substore of a ``model_store_dir`` fleet; otherwise
    the legacy single-model ``store_dir``."""
    if model is not None and fleet.spec.get("model_store_dir"):
        from perceiver_tpu.training.checkpoint import MultiModelStore

        return MultiModelStore(fleet.spec["model_store_dir"]).model(model)
    if fleet.spec.get("store_dir"):
        from perceiver_tpu.training.checkpoint import ParamsVersionStore

        return ParamsVersionStore(fleet.spec["store_dir"])
    return None


def rolling_update(fleet, version: str, *,
                   drain_timeout_s: float = 10.0,
                   model: Optional[str] = None,
                   on_replica_updated: Optional[Callable] = None) -> dict:
    """Update every replica to ``version``, one at a time. Returns a
    summary dict; raises :class:`RolloutAborted` (after rollback) on
    failure. ``on_replica_updated(rid)`` fires after each successful
    cutover — the chaos harness uses it to corrupt the new version
    mid-rollout and assert the rollback path.

    ``model`` makes this a *per-tenant* rollout on multi-model
    replicas: only that model's param set drains/swaps/rolls back, and
    only its store's CURRENT pointer moves — every other tenant's
    traffic flows uninterrupted for the whole rollout
    (docs/SERVING.md "Multi-tenancy").
    """
    store = _resolve_store(fleet, model)
    if store is None:
        raise ValueError("rolling_update needs a fleet spec with a "
                         "params version store (store_dir or "
                         "model_store_dir)")
    previous = store.current()
    order = fleet.supervisor.replicas()
    updated = []
    for rid in order:
        try:
            _cutover(fleet, rid, version,
                     drain_timeout_s=drain_timeout_s, model=model)
        except Exception as cause:  # noqa: BLE001 — typed re-raise below
            rolled_back, failed = [], []
            for done in updated:
                if previous is None:
                    failed.append(done)
                    continue
                try:
                    events_mod.emit("rollout_step", replica=done,  # graphcheck: ignore — rollout_step is replica-scoped control plane
                                    stage="rollback", version=previous)
                    _cutover(fleet, done, previous,
                             drain_timeout_s=drain_timeout_s,
                             model=model)
                    rolled_back.append(done)
                except Exception:  # noqa: BLE001 — collected, reported
                    failed.append(done)
            raise RolloutAborted(
                f"rollout of {version!r} aborted at replica {rid} "
                f"({type(cause).__name__}: {cause}); rolled back "
                f"{rolled_back or 'nothing'}"
                + (f", rollback FAILED for {failed}" if failed else ""),
                cause, rolled_back, failed) from cause
        updated.append(rid)
        if on_replica_updated is not None:
            on_replica_updated(rid)
    # all replicas cut over — only now does CURRENT move, so a crash
    # anywhere above leaves the store pointing at the old version
    store.set_current(version)
    if model is not None:
        models = dict(fleet.spec.get("models") or {})
        models[model] = version
        fleet.spec["models"] = models
        fleet.supervisor.spec["models"] = dict(models)
    else:
        fleet.spec["version"] = version
        fleet.supervisor.spec["version"] = version
    return {"version": version, "previous": previous, "model": model,
            "replicas": order, "updated": len(updated)}
