"""Lowered-graph passes: dtype policy, host transfers, donation, and
compile-cache closure, each over the StableHLO of a canonical train
step (``targets.py``).

These gate the exact defect classes previous rounds found by hand:
the round-4 HLO audit caught 9.1% of step FLOPs silently running at
the fp32 MXU rate (dtype_policy), and the axon runtime rejects host
callbacks at dispatch time (transfer_guard) — both are properties of
the lowered module, so they are checked on the lowered module.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from perceiver_tpu.analysis import hlo
from perceiver_tpu.analysis.report import (
    DtypeAllow,
    Report,
    TransferAllow,
    Violation,
    apply_dtype_allowlist,
)
from perceiver_tpu.analysis.targets import (
    CANONICAL_TARGETS,
    LoweredStep,
    StepTarget,
    lower_target,
)

# operand dtypes the MXU runs at reduced rate — any matmul-class op
# carrying one of these must be allowlisted with a reason
_SLOW_MATMUL_DTYPES = ("f32", "f64")


def dtype_policy(text: str, *, where: str,
                 allowlist: Sequence[DtypeAllow] = (),
                 require_full_bf16: bool = False,
                 ) -> Tuple[List[Violation], dict]:
    """No fp32/fp64 ``dot_general``/``convolution`` outside the
    allowlist; headline configs additionally pin the FLOP-weighted
    bf16 fraction at exactly 1.0 (the round-4 audit's regression)."""
    violations = []
    dots = list(hlo.iter_dots(text))
    slow = [d for d in dots + list(hlo.iter_convs(text))
            if d["dtype"] in _SLOW_MATMUL_DTYPES]
    _, violating = apply_dtype_allowlist(slow, tuple(allowlist))
    total = sum(d["flops"] for d in dots) or 1.0
    for rec in violating:
        share = (f", {100 * rec['flops'] / total:.1f}% of step dot-FLOPs"
                 if rec.get("flops") else "")
        violations.append(Violation(
            check="dtype_policy", where=where,
            message=f"{rec['dtype']} {rec['op']} {rec['sig']}{share} — "
                    "matmuls must run in bf16 (Policy.bf16 compute "
                    "dtype); cast the operands or add a reasoned "
                    "DtypeAllow to the target"))
    summary = hlo.dot_flop_summary(dots)
    if require_full_bf16 and summary["bf16_flop_fraction"] != 1.0:
        violations.append(Violation(
            check="dtype_policy", where=where,
            message=f"bf16_flop_fraction = "
                    f"{summary['bf16_flop_fraction']} != 1.0 on a "
                    "headline config — some dot FLOPs run at the fp32 "
                    "MXU rate (the round-4 9.1% regression class)"))
    return violations, summary


def transfer_guard(text: str, *, where: str,
                   allowlist: Sequence[TransferAllow] = (),
                   ) -> List[Violation]:
    """No host↔device transfers inside the jitted step: infeed/outfeed/
    send/recv, host-compute offload, or host-callback custom calls.
    The axon TPU runtime rejects callbacks at dispatch time, so one in
    the step graph is a guaranteed runtime failure, not a slowdown."""
    violations = []
    budgets = {a.marker: a.max_count for a in allowlist}
    for marker, count in sorted(hlo.count_host_markers(text).items()):
        allowed = budgets.get(marker, 0)
        if count > allowed:
            over = count - allowed
            violations.append(Violation(
                check="transfer_guard", where=where,
                message=f"{over} unallowlisted host-transfer marker(s) "
                        f"{marker!r} in the jitted step (total {count}, "
                        f"allowlisted {allowed}) — host syncs stall the "
                        "device pipeline and the axon runtime rejects "
                        "callbacks outright"))
    return violations


def donation_check(text: str, *, where: str,
                   expected_donated: int) -> List[Violation]:
    """Train-state buffers must be donated AND actually aliased onto
    outputs by lowering (``tf.aliasing_output``). A donated-but-
    unaliased buffer (``jax.buffer_donor``) doubles its HBM footprint
    exactly like forgetting ``donate_argnums``."""
    args = hlo.main_args(text)
    aliased = sum(1 for a in args if a["aliased"])
    donor_only = [a for a in args if a["donor_only"]]
    violations = []
    if aliased < expected_donated:
        violations.append(Violation(
            check="donation_check", where=where,
            message=f"only {aliased}/{expected_donated} train-state "
                    "buffers are donated+aliased in the lowered step — "
                    "params/optimizer state must ride donate_argnums "
                    "or peak HBM carries two copies of the state"))
    for a in donor_only:
        violations.append(Violation(
            check="donation_check", where=where,
            message=f"buffer tensor<{a['type']}> is marked donated but "
                    "lowering found no matching output to alias "
                    "(shape/dtype drift between input and output state)"))
    return violations


def recompile_budget(target: StepTarget,
                     first: Optional[LoweredStep] = None,
                     second: Optional[LoweredStep] = None,
                     ) -> Tuple[List[Violation], str]:
    """The compilation-cache key set must be closed: rebuilding a
    target's task + batch from scratch and re-lowering must reproduce
    the identical step signature (shapes, dtypes, donation layout) and
    an equal task hash. Any drift is a recompile per rebuild on the
    chip — the silent multi-minute stall class."""
    violations = []
    if first is None:
        first = lower_target(target)
    if second is None:
        second = lower_target(target)
    fp1 = hlo.module_fingerprint(first.text)
    fp2 = hlo.module_fingerprint(second.text)
    if fp1 != fp2:
        violations.append(Violation(
            check="recompile_budget", where=target.name,
            message=f"independent rebuilds lowered to different step "
                    f"signatures ({fp1} vs {fp2}) — shape/dtype drift "
                    "in the task config or batch builder means every "
                    "rebuild recompiles"))
    # task hashes are only comparable when both steps were built in
    # THIS process (str hashing is salted per process; a cache-served
    # step carries None and skips the check)
    if first.task_hash is not None and second.task_hash is not None \
            and first.task_hash != second.task_hash:
        violations.append(Violation(
            check="recompile_budget", where=target.name,
            message="task config hash differs across rebuilds — the "
                    "config dataclass carries unstable state, so jit "
                    "treats each instance as a new cache key"))
    return violations, fp1


def cache_key_stability(target: StepTarget,
                        first: Optional[LoweredStep] = None,
                        second: Optional[LoweredStep] = None,
                        ) -> Tuple[List[Violation], str]:
    """Two independent lowerings of a canonical target must hash to
    the SAME full-module text — the persistent executable cache
    (``perceiver_tpu/cache``) keys on that hash, so any trace-time
    leakage into the graph body (time, host RNG, ``id()``-derived
    names) silently zeroes the warm-start hit rate long before it
    shows up anywhere else. ``recompile_budget`` only pins the @main
    signature; this pass pins every byte. When ``first`` came from a
    persistent lowering record, the comparison spans processes — the
    exact reuse the executable cache performs."""
    violations = []
    if first is None:
        first = lower_target(target)
    if second is None:
        second = lower_target(target)
    h1 = hlo.text_hash(first.text)
    h2 = hlo.text_hash(second.text)
    if h1 != h2:
        span = ("a previous process's lowering and a fresh one"
                if first.cached else "two fresh lowerings")
        violations.append(Violation(
            check="cache_key_stability", where=target.name,
            message=f"{span} of this target hash to different module "
                    f"text ({h1[:16]} vs {h2[:16]}) — something leaks "
                    "trace-time state (time/RNG/object ids) into the "
                    "graph, which zeroes the executable-cache hit "
                    "rate; diff the two lowerings to find the "
                    "drifting op"))
    return violations, h1


# --- hbm_budget --------------------------------------------------------------
# Checked-in per-target byte budgets. The round-6 traffic work cut the
# headline step's cost-analysis bytes 38% — this pass is what keeps
# that win from silently eroding: any step whose lowered module
# accesses more bytes than its pinned budget fails the merge gate.

_HBM_MANIFEST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "hbm_budgets.json")
# budget = pinned_bytes · headroom: room for benign refactors and
# jax-version drift in the cost model, small enough that a real
# regression (a re-materialized residual, an fp32 copy) still trips
_HBM_HEADROOM = 1.05


def load_hbm_budgets(path: Optional[str] = None) -> Dict[str, dict]:
    """Target-name → ``{budget_bytes, pinned_bytes, pinned}`` from the
    checked-in manifest (empty dict when the manifest is absent — every
    canonical target then fails with a missing-budget violation, so a
    deleted manifest cannot read as a clean tree)."""
    try:
        with open(path or _HBM_MANIFEST) as f:
            return json.load(f)["targets"]
    except (OSError, KeyError, ValueError):
        return {}


def write_hbm_budgets(measured: Dict[str, float],
                      path: Optional[str] = None,
                      headroom: float = _HBM_HEADROOM,
                      note: str = "",
                      keep: Optional[Dict[str, dict]] = None) -> dict:
    """Re-baseline: pin each target's measured bytes and derive its
    budget. Only for INTENTIONAL traffic changes — see docs/ANALYSIS.md
    for the re-baseline protocol (the diff of this file is the audit
    trail of every accepted regression or win).

    ``keep`` carries already-pinned entries to copy through verbatim —
    the ``--pin-missing-hbm`` path, which budgets newly added targets
    without silently re-baselining the existing ones."""
    manifest = {
        "_comment": (
            "hbm_budget manifest — XLA cost-analysis 'bytes accessed' "
            "per canonical train step (CPU lowering, scan bodies "
            "counted once). budget_bytes = pinned_bytes x "
            f"{headroom}. Re-baseline via scripts/check.py "
            "--rebaseline-hbm after an intentional change; never edit "
            "budgets by hand to make a regression pass."),
        "targets": dict(sorted({
            **(keep or {}),
            **{name: {
                "budget_bytes": int(value * headroom),
                "pinned_bytes": int(value),
                "pinned": note,
            } for name, value in measured.items()},
        }.items())),
    }
    with open(path or _HBM_MANIFEST, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    return manifest


def hbm_budget(bytes_accessed: Optional[float], *, where: str,
               budgets: Dict[str, dict]) -> List[Violation]:
    """The lowered step's cost-analysis bytes must stay within the
    target's pinned budget. A missing budget is itself a violation —
    every canonical target must be budgeted, or adding a target would
    silently opt it out of the traffic gate."""
    entry = budgets.get(where)
    if entry is None:
        return [Violation(
            check="hbm_budget", where=where,
            message="no byte budget pinned for this target in "
                    "hbm_budgets.json — run scripts/check.py "
                    "--rebaseline-hbm and commit the manifest")]
    if bytes_accessed is None:
        return [Violation(
            check="hbm_budget", where=where,
            message="lowering exposed no cost analysis, so the byte "
                    "budget cannot be checked — run the gate on a "
                    "backend with lowering-time cost analysis (CPU)")]
    budget = float(entry["budget_bytes"])
    if bytes_accessed > budget:
        pinned = float(entry.get("pinned_bytes", budget))
        return [Violation(
            check="hbm_budget", where=where,
            message=f"bytes accessed {bytes_accessed / 1e9:.2f} GB "
                    f"exceeds the pinned budget {budget / 1e9:.2f} GB "
                    f"({100 * (bytes_accessed / pinned - 1):+.1f}% vs "
                    "the pinned baseline) — the step's HBM traffic "
                    "regressed; fix the graph or, for an intentional "
                    "change, re-baseline via scripts/check.py "
                    "--rebaseline-hbm and justify it in the PR")]
    return []


def run_graph_checks(targets: Sequence[StepTarget] = CANONICAL_TARGETS,
                     *, recompile: bool = True, cache=None) -> Report:
    """Lower each target and run all graph passes. ``recompile=False``
    skips the second lowering per target (the fast tier-1 subset).

    ``cache`` reuses persistent lowering records
    (``perceiver_tpu.cache.ExecutableCache``): the text passes then
    gate the recorded lowering — identical to a fresh one by key
    construction — and the double-lowering passes compare it against
    ONE fresh trace, which turns ``cache_key_stability`` into a
    cross-process check and halves (``--graph``) or removes
    (``--graph --fast``) the lowering bill of a warm run."""
    from perceiver_tpu.analysis import shardcheck

    report = Report()
    fingerprints = {}
    budgets = load_hbm_budgets()
    shard_budgets = shardcheck.load_shard_budgets()
    for target in targets:
        lowered = lower_target(target, cache=cache)
        report.extend(hbm_budget(lowered.bytes_accessed,
                                 where=target.name, budgets=budgets))
        report.ran("hbm_budget")
        if target.mesh is not None:
            vs, _inventory = shardcheck.run_shard_passes(
                lowered, budgets=shard_budgets)
            report.extend(vs)
            report.ran("collective_budget")
            report.ran("replication_check")
            report.ran("per_shard_hbm_budget")
        vs, _summary = dtype_policy(
            lowered.text, where=target.name,
            allowlist=target.dtype_allow,
            require_full_bf16=target.headline)
        report.extend(vs)
        report.ran("dtype_policy")
        report.extend(transfer_guard(
            lowered.text, where=target.name,
            allowlist=target.transfer_allow))
        report.ran("transfer_guard")
        report.extend(donation_check(
            lowered.text, where=target.name,
            expected_donated=lowered.expected_donated))
        report.ran("donation_check")
        if recompile:
            # the second lowering is always fresh — when `lowered`
            # came from the cache this compares across processes.
            # want_compiled=False: the stability passes only compare
            # StableHLO text, so mesh targets skip the XLA compile
            second = lower_target(target, want_compiled=False)
            vs, fp = recompile_budget(target, first=lowered,
                                      second=second)
            report.extend(vs)
            report.ran("recompile_budget")
            vs, _h = cache_key_stability(target, first=lowered,
                                         second=second)
            report.extend(vs)
            report.ran("cache_key_stability")
            fingerprints[target.name] = fp
    if recompile:
        # declared signature twins: a target whose whole point is that
        # it lowers onto ANOTHER target's compile key (the multi-tenant
        # decode round — tenancy is host-side state, so admitting a
        # tenant must mint zero new executables). Equality is ASSERTED
        # when both ends were lowered this run, and the twin is
        # excluded from the distinct-targets collapse check below.
        twins = {t.name: t.signature_twin for t in targets
                 if t.signature_twin}
        for name, twin in twins.items():
            if name not in fingerprints or twin not in fingerprints:
                continue  # partial run (e.g. a single-target tier)
            if fingerprints[name] != fingerprints[twin]:
                report.add(Violation(
                    check="recompile_budget", where=name,
                    message=f"declared signature twin of {twin!r} but "
                            f"the fingerprints diverged "
                            f"({fingerprints[name]} vs "
                            f"{fingerprints[twin]}) — the twin config "
                            "now compiles its own executable, which "
                            "for the multi-tenant round means tenant "
                            "admission costs a mid-traffic compile"))
        primary = {n: fp for n, fp in fingerprints.items()
                   if n not in twins}
        if len(set(primary.values())) < len(primary):
            dupes = {n: fp for n, fp in primary.items()
                     if list(primary.values()).count(fp) > 1}
            report.add(Violation(
                check="recompile_budget", where=",".join(sorted(dupes)),
                message=f"distinct targets share a step signature "
                        f"{dupes} — two canonical configs collapsed "
                        "onto one compile key, so one of them is not "
                        "being checked"))
    return report
