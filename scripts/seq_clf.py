#!/usr/bin/env python
"""Sentiment-classification CLI (reference ``scripts/seq_clf.py``),
with MLM transfer learning and encoder freezing.

Two-phase recipe (mirrors README.md:77-107):

    python scripts/seq_clf.py fit \\
      --model.mlm_ckpt=logs/mlm/version_0/checkpoints \\
      --model.freeze_encoder=true --trainer.max_epochs=15 \\
      --experiment=seq_clf

    python scripts/seq_clf.py fit \\
      --model.clf_ckpt=logs/seq_clf/version_0/checkpoints \\
      --optimizer.init_args.lr=1e-4 --trainer.max_epochs=5
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from perceiver_tpu.data import IMDBDataModule  # noqa: E402
from perceiver_tpu.tasks import TextClassifierTask  # noqa: E402
from perceiver_tpu.utils.config import CLI, Link  # noqa: E402

TRAINER_YAML = os.path.join(os.path.dirname(__file__), "trainer.yaml")


def main(args=None, run=True):
    return CLI(
        TextClassifierTask,
        datamodules={"IMDBDataModule": IMDBDataModule},
        default_datamodule="IMDBDataModule",
        default_config_files=[TRAINER_YAML],
        defaults={  # reference seq_clf.py:13-22
            "experiment": "seq_clf",
            "model.num_classes": 2,
            "model.num_decoder_cross_attention_heads": 1,
        },
        links=[
            Link("data.vocab_size", "model.vocab_size",
                 apply_on="instantiate"),
            Link("data.max_seq_len", "model.max_seq_len",
                 apply_on="instantiate"),
        ],
        description=__doc__,
        run=run,
        args=args,
    )


if __name__ == "__main__":
    main()
