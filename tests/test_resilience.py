"""Chaos tests for the resilience layer (ISSUE 5, docs/RESILIENCE.md).

Every defense is pinned against its deterministic fault, in-process —
plus the crash-only checkpoint contract in subprocess kill-during-save
form: the run reaches its target step with verified-checkpoint
restore, and serving keeps answering with typed errors only.
"""

import dataclasses
import io
import os
import signal
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from perceiver_tpu.data.core import ArrayDataset, BatchIterator
from perceiver_tpu.data.prefetch import LoaderStalled, PrefetchIterator
from perceiver_tpu.resilience import (
    CircuitBreaker,
    FaultInjected,
    FaultPlan,
    NonFiniteLossError,
    StepGuard,
    faults,
)
from perceiver_tpu.resilience import breaker as breaker_mod
from perceiver_tpu.resilience import guard as guard_mod
from perceiver_tpu.training.checkpoint import (
    CORRUPT,
    UNVERIFIED,
    VERIFIED,
    CheckpointHook,
    CheckpointIntegrityError,
    _truncate_one_blob,
    restore_params,
    verify_step,
)
from perceiver_tpu.training.state import TrainState

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No fault plan may leak between tests (module-global arming)."""
    faults.disarm()
    yield
    faults.disarm()


# --- faults: the injection framework ----------------------------------------


class TestFaultPlan:
    def test_parse_and_window(self):
        plan = FaultPlan.parse(
            "train.nonfinite@at=2,count=3;serve.dispatch")
        spec = plan.specs["train.nonfinite"]
        assert (spec.at, spec.count) == (2, 3)
        # occurrences 0,1 inert; 2,3,4 fire; 5+ inert again
        fires = [plan.fire("train.nonfinite") is not None
                 for _ in range(6)]
        assert fires == [False, False, True, True, True, False]
        # default window: first occurrence only
        assert plan.fire("serve.dispatch") is not None
        assert plan.fire("serve.dispatch") is None
        assert plan.counts() == {"train.nonfinite": 3,
                                 "serve.dispatch": 1}

    def test_unknown_point_and_bad_params_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            FaultPlan.parse("loader.exploded")
        with pytest.raises(ValueError, match="bad fault param"):
            FaultPlan.parse("loader.exception@when=3")
        with pytest.raises(ValueError, match="duplicate"):
            FaultPlan.parse("serve.dispatch;serve.dispatch@at=1")
        with pytest.raises(ValueError, match="empty"):
            FaultPlan.parse("  ;  ")

    def test_unarmed_is_inert(self):
        assert faults.active() is None
        assert not faults.fire("serve.dispatch")
        assert not faults.armed("serve.dispatch")
        faults.maybe_raise("serve.dispatch")  # no-op, no raise
        assert faults.counts() == {}

    def test_arm_disarm_and_maybe_raise(self):
        faults.arm("serve.dispatch@count=2")
        assert faults.armed("serve.dispatch")
        with pytest.raises(FaultInjected, match="serve.dispatch"):
            faults.maybe_raise("serve.dispatch")
        with pytest.raises(FaultInjected):
            faults.maybe_raise("serve.dispatch")
        faults.maybe_raise("serve.dispatch")  # window spent
        faults.disarm()
        assert not faults.armed("serve.dispatch")

    def test_forever_window(self):
        plan = faults.arm("train.nonfinite@count=-1")
        assert all(plan.fire("train.nonfinite") for _ in range(50))


# --- circuit breaker --------------------------------------------------------


class TestCircuitBreaker:
    def test_full_state_machine(self):
        now = [0.0]
        seen = []
        b = CircuitBreaker(failure_threshold=2, reset_timeout_s=5.0,
                           clock=lambda: now[0],
                           on_transition=lambda o, n: seen.append((o, n)))
        assert b.allow() and b.state == breaker_mod.CLOSED
        b.record_failure()
        assert b.state == breaker_mod.CLOSED  # below threshold
        b.record_failure()
        assert b.state == breaker_mod.OPEN
        assert not b.allow()
        assert b.retry_after() == pytest.approx(5.0)
        now[0] = 3.0
        assert not b.allow() and b.retry_after() == pytest.approx(2.0)
        now[0] = 5.5
        assert b.allow()  # half-open probe
        assert b.state == breaker_mod.HALF_OPEN
        assert not b.allow()  # only one probe until its outcome lands
        b.record_failure()  # failed probe
        assert b.state == breaker_mod.OPEN
        now[0] = 11.0
        assert b.allow()
        b.record_success()
        assert b.state == breaker_mod.CLOSED and b.allow()
        assert seen == [
            (breaker_mod.CLOSED, breaker_mod.OPEN),
            (breaker_mod.OPEN, breaker_mod.HALF_OPEN),
            (breaker_mod.HALF_OPEN, breaker_mod.OPEN),
            (breaker_mod.OPEN, breaker_mod.HALF_OPEN),
            (breaker_mod.HALF_OPEN, breaker_mod.CLOSED),
        ]

    def test_success_resets_failure_streak(self):
        b = CircuitBreaker(failure_threshold=2, reset_timeout_s=1.0)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == breaker_mod.CLOSED

    def test_callback_may_read_state(self):
        """Regression: on_transition fires outside the breaker lock, so
        a metrics/health callback reading .state must not deadlock."""
        states = []
        b = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0,
                           on_transition=lambda o, n:
                           states.append(b.state))
        b.record_failure()
        assert states == [breaker_mod.OPEN]


# --- step guard -------------------------------------------------------------


class TestStepGuard:
    def test_halt_names_exact_step_inside_block(self):
        g = StepGuard(guard_mod.HALT)
        assert g.observe([1.0, 0.5], first_step=10) == guard_mod.OK
        with pytest.raises(NonFiniteLossError,
                           match=r"step 14 \(terminate_on_nan\)"):
            g.observe([0.4, np.nan, 0.3], first_step=12)

    def test_skip_counts_and_streak_rewinds(self):
        g = StepGuard(guard_mod.SKIP, streak_to_rewind=3, max_rewinds=1)
        assert g.observe([np.nan, 1.0, np.inf], 0) == guard_mod.OK
        assert g.skipped_total == 2  # isolated bads, streak broken
        assert g.observe([np.nan, np.nan, np.nan], 3) == guard_mod.REWIND
        assert g.rewinds == 1
        # budget spent: the next streak halts with a typed error
        with pytest.raises(NonFiniteLossError, match="rewind budget"):
            g.observe([np.nan] * 3, 6)

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            StepGuard("never-heard-of-it")

    def test_wrapped_step_skips_update_and_reports_loss(self):
        """Device half: a non-finite loss leaves params/opt_state
        untouched while rng/step advance; finite steps train."""
        def train_step(state, batch):
            grad = batch["x"].mean()
            params = jax.tree.map(lambda p: p - 0.1 * grad, state.params)
            rng, _ = jax.random.split(state.rng)
            new = dataclasses.replace(state, params=params, rng=rng,
                                      step=state.step + 1)
            return new, {"loss": grad}

        guarded = jax.jit(guard_mod.wrap_train_step(train_step))
        params = {"w": jnp.ones((3,))}
        tx = optax.sgd(0.1)
        state = TrainState.create(params, tx.init(params),
                                  jax.random.key(0))
        good = {"x": jnp.full((4,), 2.0)}
        bad = {"x": jnp.full((4,), jnp.nan)}

        s1, m1, l1 = guarded(state, good)
        assert np.isfinite(float(l1[0]))
        np.testing.assert_allclose(np.asarray(s1.params["w"]), 0.8)
        s2, m2, l2 = guarded(s1, bad)
        assert not np.isfinite(float(l2[0]))
        # skipped: params identical, but step and rng advanced
        np.testing.assert_array_equal(np.asarray(s2.params["w"]),
                                      np.asarray(s1.params["w"]))
        assert int(s2.step) == int(s1.step) + 1
        assert not np.array_equal(jax.random.key_data(s2.rng),
                                  jax.random.key_data(s1.rng))

    def test_wrapped_multi_threads_per_step_losses(self):
        def train_step(state, batch):
            loss = batch["x"].mean()
            new = dataclasses.replace(
                state,
                params=jax.tree.map(lambda p: p - loss, state.params),
                step=state.step + 1)
            return new, {"loss": loss}

        multi = jax.jit(guard_mod.wrap_train_step_multi(train_step))
        params = {"w": jnp.zeros(())}
        tx = optax.sgd(0.1)
        state = TrainState.create(params, tx.init(params),
                                  jax.random.key(0))
        stacked = {"x": jnp.stack([jnp.full((2,), 1.0),
                                   jnp.full((2,), jnp.nan),
                                   jnp.full((2,), 3.0)])}
        out, metrics, losses = multi(state, stacked)
        got = np.asarray(losses)
        assert got.shape == (3,)
        assert np.isfinite(got[0]) and not np.isfinite(got[1]) \
            and np.isfinite(got[2])
        # only the two finite steps applied: 0 - 1 - 3 = -4
        assert float(out.params["w"]) == pytest.approx(-4.0)
        assert int(out.step) == 3


# --- checkpoint integrity ---------------------------------------------------


def _tiny_state(value: float = 1.0, step: int = 0) -> TrainState:
    params = {"w": jnp.arange(8.0) * value, "b": jnp.ones((2,)) * value}
    tx = optax.adamw(1e-3)
    state = TrainState.create(params, tx.init(params), jax.random.key(3))
    return dataclasses.replace(state, step=jnp.asarray(step))


class TestCheckpointIntegrity:
    def test_save_seals_verified_manifest(self, tmp_path):
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="")
        hook.save(1, _tiny_state(1.0, 1), {})
        hook.wait()
        step_dir = str(tmp_path / "ck" / "1")
        assert os.path.exists(os.path.join(step_dir,
                                           "manifest.sha256.json"))
        assert hook.verify(1) == VERIFIED

    def test_truncated_blob_falls_back_to_verified(self, tmp_path):
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="",
                              max_to_keep=3)
        hook.save(1, _tiny_state(1.0, 1), {})
        hook.save(2, _tiny_state(7.0, 2), {})
        hook.wait()
        _truncate_one_blob(str(tmp_path / "ck" / "2"))
        assert hook.verify(2) == CORRUPT
        with pytest.warns(UserWarning, match="manifest"):
            got = hook.restore_latest(_tiny_state())
        assert int(got.step) == 1  # newest VERIFIED, not newest
        np.testing.assert_array_equal(np.asarray(got.params["w"]),
                                      np.arange(8.0))

    def test_all_corrupt_raises_typed_error(self, tmp_path):
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="")
        hook.save(1, _tiny_state(), {})
        hook.wait()
        _truncate_one_blob(str(tmp_path / "ck" / "1"))
        with pytest.raises(CheckpointIntegrityError), \
                pytest.warns(UserWarning, match="manifest"):
            hook.restore_latest(_tiny_state())
        # NOT a ValueError/KeyError: the trainer's optimizer-mismatch
        # degrade path must never catch corruption
        assert not issubclass(CheckpointIntegrityError,
                              (ValueError, KeyError))

    def test_manifestless_step_is_legacy_restorable(self, tmp_path):
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="")
        hook.save(1, _tiny_state(2.0, 1), {})
        hook.wait()
        os.unlink(str(tmp_path / "ck" / "1" / "manifest.sha256.json"))
        assert hook.verify(1) == UNVERIFIED
        got = hook.restore_latest(_tiny_state())
        assert int(got.step) == 1

    def test_restore_params_skips_corrupt_step(self, tmp_path):
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="",
                              max_to_keep=3)
        hook.save(1, _tiny_state(1.0, 1), {})
        hook.save(2, _tiny_state(9.0, 2), {})
        hook.wait()
        _truncate_one_blob(str(tmp_path / "ck" / "2"))
        with pytest.warns(UserWarning, match="corrupt"):
            params = restore_params(str(tmp_path / "ck"))
        np.testing.assert_array_equal(np.asarray(params["w"]),
                                      np.arange(8.0))

    def test_empty_dir_still_returns_none(self, tmp_path):
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="")
        assert hook.restore_latest(_tiny_state()) is None

    def test_kill_during_save_subprocess(self, tmp_path):
        """Crash-only contract, proven with a real SIGKILL in a fresh
        subprocess: the victim dies mid-save, the survivor steps are
        restorable, and the restored values are bitwise-exact for
        whichever step survived."""
        script = textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {_REPO!r})
            from tests.test_resilience import _tiny_state
            from perceiver_tpu.training.checkpoint import CheckpointHook

            hook = CheckpointHook({str(tmp_path / "ck")!r},
                                  max_to_keep=5, monitor="")
            hook.save(1, _tiny_state(1.0, 1), {{}})
            hook.save(2, _tiny_state(3.0, 2), {{}})  # armed kill fires
            hook.wait()
            print("SURVIVED-THE-KILL")
        """)
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     PERCEIVER_FAULTS="ckpt.kill_during_save@at=1"),
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                    proc.stderr)
        assert "SURVIVED-THE-KILL" not in proc.stdout

        hook = CheckpointHook(str(tmp_path / "ck"), monitor="")
        # save 1 was sealed before the kill — always verified
        assert hook.verify(1) == VERIFIED
        got = hook.restore_latest(_tiny_state())
        assert got is not None
        expect = {1: np.arange(8.0), 2: np.arange(8.0) * 3.0}
        np.testing.assert_array_equal(np.asarray(got.params["w"]),
                                      expect[int(got.step)])
        # cleanup any partially-committed junk never breaks _steps()
        assert all(isinstance(s, int) for s in hook._steps())

    def test_truncate_fault_seam(self, tmp_path):
        faults.arm("ckpt.truncate@at=0")
        hook = CheckpointHook(str(tmp_path / "ck"), monitor="")
        hook.save(1, _tiny_state(), {})
        hook.wait()  # finalize seals the manifest, then the fault bites
        assert hook.verify(1) == CORRUPT
        assert verify_step(str(tmp_path / "ck" / "1")) == CORRUPT


# --- supervised prefetch ----------------------------------------------------


def _loader(n=23, bs=4):
    ds = ArrayDataset(x=np.arange(n, dtype=np.int32))
    return BatchIterator(ds, bs, shuffle=True, seed=5)


class TestSupervisedPrefetch:
    def test_transient_failure_restarts_with_identical_stream(self):
        faults.arm("loader.exception@at=3,count=2")
        pf = PrefetchIterator(_loader(), max_restarts=3, backoff_s=0.0)
        got = [b["x"].copy() for b in pf]
        want = [b["x"].copy() for b in _loader()]
        assert pf.restarts == 2
        assert len(got) == len(want)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)  # no dups, no gaps

    def test_poison_pill_budget_reraises(self):
        faults.arm("loader.exception@at=1,count=-1")
        pf = PrefetchIterator(_loader(), max_restarts=2, backoff_s=0.0)
        with pytest.raises(FaultInjected):
            list(pf)
        assert pf.restarts == 2  # budget fully spent first

    def test_generator_inner_never_restarts(self):
        def gen():
            yield {"x": np.zeros(2)}
            raise RuntimeError("boom")

        pf = PrefetchIterator(gen(), max_restarts=5, backoff_s=0.0)
        with pytest.raises(RuntimeError, match="boom"):
            list(pf)
        assert pf.restarts == 0

    def test_stall_watchdog_restarts(self):
        faults.arm("loader.stall@at=2,count=1,value=5.0")
        pf = PrefetchIterator(_loader(), max_restarts=2, backoff_s=0.0,
                              stall_timeout_s=0.4)
        got = [b["x"].copy() for b in pf]
        want = [b["x"].copy() for b in _loader()]
        assert pf.restarts == 1
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)

    def test_stall_without_budget_raises_typed(self):
        faults.arm("loader.stall@at=0,count=1,value=5.0")
        pf = PrefetchIterator(_loader(), max_restarts=0,
                              stall_timeout_s=0.3)
        with pytest.raises(LoaderStalled):
            list(pf)

    def test_ctor_validation(self):
        with pytest.raises(ValueError):
            PrefetchIterator(_loader(), max_restarts=-1)
        with pytest.raises(ValueError):
            PrefetchIterator(_loader(), stall_timeout_s=0.0)


# --- download retries -------------------------------------------------------


class TestDownloadRetries:
    def _fetch(self, monkeypatch, responses, **kwargs):
        """Drive fetch() against a scripted urlopen: each entry is an
        Exception to raise or bytes to serve."""
        import urllib.request

        from perceiver_tpu.data import download

        monkeypatch.delenv("PERCEIVER_TPU_OFFLINE", raising=False)
        download._failed_urls.clear()
        calls = []

        def fake_urlopen(url, timeout=None):
            action = responses[min(len(calls), len(responses) - 1)]
            calls.append(url)
            if isinstance(action, Exception):
                raise action
            return io.BytesIO(action)

        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        return calls, download.fetch("http://x.test/f",
                                     kwargs.pop("dest"),
                                     backoff_s=0.0, **kwargs)

    def test_transient_error_retried_then_succeeds(self, tmp_path,
                                                   monkeypatch):
        dest = str(tmp_path / "out")
        calls, ok = self._fetch(
            monkeypatch, [OSError("reset"), OSError("reset"), b"payload"],
            dest=dest, retries=3)
        assert ok and len(calls) == 3
        with open(dest, "rb") as f:
            assert f.read() == b"payload"

    def test_budget_exhausted_returns_false_once(self, tmp_path,
                                                 monkeypatch):
        from perceiver_tpu.data import download

        dest = str(tmp_path / "out")
        calls, ok = self._fetch(monkeypatch, [OSError("down")],
                                dest=dest, retries=3)
        assert not ok and len(calls) == 3
        assert not os.path.exists(dest)
        # the URL is poisoned for this process: no further attempts
        assert not download.fetch("http://x.test/f", dest)
        assert len(calls) == 3

    def test_sha256_mismatch_retries_and_never_publishes(self, tmp_path,
                                                         monkeypatch):
        import hashlib

        dest = str(tmp_path / "out")
        good = hashlib.sha256(b"good").hexdigest()
        calls, ok = self._fetch(monkeypatch, [b"evil", b"evil", b"good"],
                                dest=dest, retries=3, sha256=good)
        assert ok and len(calls) == 3
        with open(dest, "rb") as f:
            assert f.read() == b"good"
        calls, ok = self._fetch(monkeypatch, [b"evil"], dest=dest + "2",
                                retries=2, sha256=good)
        assert not ok
        assert not os.path.exists(dest + "2")  # corrupt never published


# --- serving: breaker, typed errors, health ---------------------------------


VOCAB = 110


def _tiny_mlm_task():
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    return MaskedLanguageModelTask(
        vocab_size=VOCAB, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")


def _request(batch=1, length=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(3, VOCAB,
                                      (batch, length)).astype(np.int32),
            "pad_mask": np.zeros((batch, length), bool)}


@pytest.fixture()
def clocked_engine():
    """Warmed single-bucket engine with an injectable breaker clock."""
    from perceiver_tpu.serving import ServingEngine

    now = [0.0]
    engine = ServingEngine(_tiny_mlm_task(), batch_buckets=(1,),
                           seq_buckets=(16,),
                           breaker_failure_threshold=2,
                           breaker_reset_s=10.0,
                           breaker_clock=lambda: now[0])
    return engine, now


class TestServingResilience:
    def test_breaker_opens_unavailable_then_probe_recovers(
            self, clocked_engine):
        from perceiver_tpu.serving import HealthState, Unavailable

        engine, now = clocked_engine
        assert engine.health.state is HealthState.READY
        engine.dispatch(_request())  # healthy baseline

        faults.arm("serve.dispatch@at=0,count=3")
        for _ in range(2):
            with pytest.raises(FaultInjected):
                engine.dispatch(_request())
        # threshold 2 reached: sole bucket open ⇒ UNAVAILABLE, and
        # requests now fail fast with the typed error + retry hint
        assert engine.health.state is HealthState.UNAVAILABLE
        with pytest.raises(Unavailable) as exc:
            engine.dispatch(_request())
        assert exc.value.reason == "circuit_open"
        assert exc.value.retry_after_s == pytest.approx(10.0)
        assert not engine.health.ready

        now[0] = 11.0  # cooldown over: half-open probe — fails (3rd)
        with pytest.raises(FaultInjected):
            engine.dispatch(_request())
        with pytest.raises(Unavailable):
            engine.dispatch(_request())

        now[0] = 22.0  # next probe succeeds: recovery
        res = engine.dispatch(_request())
        assert res.batch == 1
        assert engine.health.state is HealthState.READY
        assert engine.health.ready

        m = engine.metrics
        assert m.get("serving_dispatch_failures_total").value == 3
        assert m.get("serving_unavailable_total").value_of(
            reason="circuit_open") == 2
        t = m.get("serving_breaker_transitions_total")
        assert t.value_of(bucket="b1_s16", to="open") == 2
        assert t.value_of(bucket="b1_s16", to="closed") == 1

    def test_request_too_large_does_not_trip_breaker(self,
                                                     clocked_engine):
        from perceiver_tpu.serving import RequestTooLarge

        engine, _ = clocked_engine
        with pytest.raises(RequestTooLarge):
            engine.dispatch(_request(batch=2))
        assert engine.metrics.get(
            "serving_dispatch_failures_total").value == 0
        engine.dispatch(_request())  # still serving

    def test_batcher_isolates_batch_with_typed_per_request_errors(
            self, clocked_engine):
        from perceiver_tpu.serving import (
            BatchError,
            MicroBatcher,
            materialize,
        )

        engine, _ = clocked_engine

        def runner(payloads):
            res = engine.dispatch(payloads[0])
            return [materialize(res, engine.graph)]

        batcher = MicroBatcher(runner, max_batch=1, max_delay_ms=0.5,
                               metrics=engine.metrics)
        try:
            faults.arm("serve.dispatch@at=0,count=1")
            fut = batcher.submit(_request())
            with pytest.raises(BatchError) as exc:
                fut.result(timeout=30)
            assert isinstance(exc.value.cause, FaultInjected)
            # worker survived: the next request is served normally
            out = batcher.submit(_request()).result(timeout=30)
            assert "topk_ids" in out
            m = engine.metrics
            assert m.get("serving_failed_batches_total").value == 1
            assert m.get("serving_requests_total").value_of(
                outcome="error") == 1
            assert m.get("serving_requests_total").value_of(
                outcome="ok") == 1
        finally:
            batcher.close()

    def test_unavailable_passes_through_batcher_typed(self,
                                                      clocked_engine):
        from perceiver_tpu.serving import (
            MicroBatcher,
            Unavailable,
            materialize,
        )

        engine, _ = clocked_engine
        faults.arm("serve.dispatch@at=0,count=2")
        for _ in range(2):
            with pytest.raises(FaultInjected):
                engine.dispatch(_request())

        def runner(payloads):
            res = engine.dispatch(payloads[0])
            return [materialize(res, engine.graph)]

        batcher = MicroBatcher(runner, max_batch=1, max_delay_ms=0.5,
                               metrics=engine.metrics)
        try:
            with pytest.raises(Unavailable):
                batcher.submit(_request()).result(timeout=30)
            assert engine.metrics.get("serving_requests_total").value_of(
                outcome="unavailable") == 1
        finally:
            batcher.close()

    def test_health_metrics_exported(self, clocked_engine):
        engine, _ = clocked_engine
        m = engine.metrics
        assert m.get("serving_ready").value == 1
        assert m.get("serving_health_state").value == 1  # READY
        faults.arm("serve.dispatch@at=0,count=2")
        for _ in range(2):
            with pytest.raises(FaultInjected):
                engine.dispatch(_request())
        assert m.get("serving_ready").value == 0
        assert m.get("serving_health_state").value == 3  # UNAVAILABLE
        trans = m.get("serving_health_transitions_total")
        assert trans.value_of(**{"from": "ready",
                                 "to": "unavailable"}) == 1


# --- trainer end-to-end (slow) ----------------------------------------------


def _trainer(tmp_path, tag, **overrides):
    from perceiver_tpu.data import MNISTDataModule
    from perceiver_tpu.training import Trainer, TrainerConfig

    from tests.test_training import ADAMW, small_image_task

    dm = MNISTDataModule(data_dir=str(tmp_path / "nope"), batch_size=16,
                         synthetic_train_size=96, synthetic_test_size=32)
    cfg = dict(max_steps=6, max_epochs=8, num_sanity_val_steps=0,
               log_every_n_steps=1,
               default_root_dir=str(tmp_path / f"logs_{tag}"),
               enable_checkpointing=False, prefetch_batches=0)
    cfg.update(overrides)
    return Trainer(small_image_task(), dm, TrainerConfig(**cfg),
                   optimizer_init=ADAMW)


def _params_finite(state):
    return all(bool(np.isfinite(np.asarray(leaf)).all())
               for leaf in jax.tree.leaves(state.params))


def test_trainer_skip_policy_survives_isolated_nan_steps(tmp_path):
    """Two poisoned steps are skipped (no update applied), counted, and
    the run reaches its target with finite params — the defense for
    trainer.py's old one-bad-batch-kills-the-run mode."""
    trainer = _trainer(tmp_path, "skip", nonfinite_policy="skip",
                       fault_plan="train.nonfinite@at=2,count=2")
    state = trainer.fit()
    assert int(state.step) == 6
    assert trainer._guard.skipped_total == 2
    assert trainer._guard.rewinds == 0
    assert _params_finite(state)


def test_trainer_streak_rewinds_from_verified_anchor(tmp_path):
    """A persistent bad window triggers anchor restore + deterministic
    data replay; the run completes once the window passes."""
    trainer = _trainer(tmp_path, "rewind", max_steps=8,
                       nonfinite_policy="skip", nonfinite_streak=3,
                       nonfinite_max_rewinds=2,
                       fault_plan="train.nonfinite@at=3,count=5")
    state = trainer.fit()
    assert int(state.step) == 8
    assert trainer._guard.rewinds >= 1
    assert _params_finite(state)
    # the anchor the rewind used is a sealed, verified checkpoint
    guard_dir = os.path.join(trainer.log_dir, "checkpoints-guard")
    hook = CheckpointHook(guard_dir, monitor="")
    steps = hook._steps()
    assert steps and hook.verify(steps[0]) == VERIFIED


def test_terminate_on_nan_names_first_bad_step_in_block(tmp_path):
    """Satellite: with steps_per_execution the halt names the exact
    in-block step (previously only the block-boundary mean was seen)."""
    trainer = _trainer(tmp_path, "halt", max_steps=9, max_epochs=3,
                       steps_per_execution=3, log_every_n_steps=50,
                       terminate_on_nan=True,
                       fault_plan="train.nonfinite@at=4,count=1")
    with pytest.raises(FloatingPointError,
                       match=r"step 5 \(terminate_on_nan\)"):
        trainer.fit()


def test_preemption_fault_roundtrip_with_verified_checkpoint(tmp_path):
    """The _handle_preemption path (trainer.py:378): injected
    preemption → sealed save into checkpoints-preempt → clean stop →
    resume_from_checkpoint continues to the target step."""
    trainer = _trainer(tmp_path, "pre", max_steps=20,
                       fault_plan="train.preempt@at=3")
    trainer.fit()
    stopped = trainer.global_step
    assert 0 < stopped < 20
    preempt_dir = os.path.join(trainer.log_dir, "checkpoints-preempt")
    hook = CheckpointHook(preempt_dir, monitor="")
    assert hook.verify(stopped) == VERIFIED

    faults.disarm()
    resume = _trainer(tmp_path, "pre2", max_steps=stopped + 2,
                      resume_from_checkpoint=preempt_dir)
    state = resume.fit()
    assert int(state.step) == stopped + 2


def test_trainer_loader_crash_survived_by_supervisor(tmp_path):
    """Loader exceptions mid-epoch restart the prefetch producer; the
    run reaches its target step (prefetch.py's old line-70 death)."""
    trainer = _trainer(tmp_path, "loader", prefetch_batches=2,
                       fault_plan="loader.exception@at=1,count=2")
    state = trainer.fit()
    assert int(state.step) == 6
    assert _params_finite(state)


def test_trainer_rejects_unknown_guard_policy(tmp_path):
    with pytest.raises(ValueError, match="nonfinite_policy"):
        _trainer(tmp_path, "bad", nonfinite_policy="retry-forever")
