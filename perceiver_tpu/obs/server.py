"""Stdlib HTTP endpoint for the observability plane.

One ``ThreadingHTTPServer`` on loopback serving:

``/metrics``            Prometheus exposition (aggregated fleet text,
                        or a single registry's render — whatever
                        callable the owner wires in)
``/healthz``            JSON health snapshot (200 when the owner's
                        health callable says so, 503 otherwise)
``/traces``             JSON list of buffered trace ids
``/traces/<id>``        one trace's spans as JSON
``/profile?seconds=N``  on-demand ``jax.profiler`` capture into the
                        configured profile dir (returns the capture
                        path); 501 when no dir is configured

No dependency beyond the stdlib; all handlers are read-only except
``/profile``, which is bounded (one capture at a time, N clamped).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from perceiver_tpu.obs import trace as trace_mod

__all__ = ["ObsServer"]

_MAX_PROFILE_SECONDS = 30.0


class ObsServer:
    """Own one background HTTP server exposing metrics/health/traces.

    ``metrics_fn`` returns exposition text; ``health_fn`` returns a
    JSON-able dict with a truthy ``"ok"`` key when healthy.
    """

    def __init__(self, *, metrics_fn: Callable[[], str],
                 health_fn: Optional[Callable[[], dict]] = None,
                 trace_buffer: Optional[trace_mod.TraceBuffer] = None,
                 profile_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self._metrics_fn = metrics_fn
        self._health_fn = health_fn or (lambda: {"ok": True})
        self._buffer = (trace_buffer if trace_buffer is not None
                        else trace_mod.default_buffer())
        self._profile_dir = profile_dir
        self._profile_lock = threading.Lock()
        owner = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: tests hit this
                pass

            def do_GET(self):
                owner._route(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(2.0)

    # -- request routing ---------------------------------------------------

    def _route(self, handler: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(handler.path)
        path = parsed.path.rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(handler, 200, self._metrics_fn(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                health = self._health_fn()
                code = 200 if health.get("ok") else 503
                self._send_json(handler, code, health)
            elif path == "/traces":
                self._send_json(handler, 200,
                                {"traces": self._buffer.trace_ids()})
            elif path.startswith("/traces/"):
                trace_id = path[len("/traces/"):]
                spans = self._buffer.get(trace_id)
                if spans is None:
                    self._send_json(handler, 404,
                                    {"error": "unknown trace",
                                     "trace_id": trace_id})
                else:
                    self._send_json(handler, 200,
                                    {"trace_id": trace_id,
                                     "spans": spans})
            elif path == "/profile":
                q = parse_qs(parsed.query)
                seconds = float(q.get("seconds", ["1"])[0])
                self._profile(handler, seconds)
            else:
                self._send_json(handler, 404, {"error": "not found",
                                               "path": path})
        except BrokenPipeError:
            pass  # client went away mid-reply — nothing to salvage
        except Exception as e:  # endpoint must answer, never hang
            try:
                self._send_json(handler, 500, {"error": str(e)})
            except OSError:
                pass  # connection already unusable

    def _profile(self, handler: BaseHTTPRequestHandler,
                 seconds: float) -> None:
        if not self._profile_dir:
            self._send_json(handler, 501,
                            {"error": "no profile_dir configured"})
            return
        seconds = max(0.05, min(seconds, _MAX_PROFILE_SECONDS))
        if not self._profile_lock.acquire(blocking=False):
            self._send_json(handler, 409,
                            {"error": "capture already running"})
            return
        try:
            import jax

            jax.profiler.start_trace(self._profile_dir)
            time.sleep(seconds)
            jax.profiler.stop_trace()
        except Exception as e:  # profiler backend drift — report, don't die
            self._send_json(handler, 500, {"error": str(e)})
            return
        finally:
            self._profile_lock.release()
        self._send_json(handler, 200, {"ok": True,
                                       "dir": self._profile_dir,
                                       "seconds": seconds})

    # -- low-level senders -------------------------------------------------

    @staticmethod
    def _send(handler, code: int, body: str, content_type: str) -> None:
        data = body.encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    def _send_json(self, handler, code: int, obj: dict) -> None:
        self._send(handler, code, json.dumps(obj, sort_keys=True),
                   "application/json")
