"""BASELINE.md large-config coverage.

Two layers of proof, because CPU can't *execute* pod-scale configs:

- ``jax.eval_shape`` over the TRUE full-size configs (224×224/512-latent
  classifier; 1024×512-latent / 12-block / seq-2048 MLM) — abstract
  evaluation costs no FLOPs or memory yet walks every shape contract in
  init, forward, and loss.
- executed one-step training on structure-faithful reduced configs over
  real dp×tp meshes (8 virtual CPU devices), checking finite loss and
  that tensor-parallel parameter shards actually differ per device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from perceiver_tpu.parallel import batch_sharding, make_mesh, shard_params
from perceiver_tpu.ops.policy import Policy
from perceiver_tpu.tasks import (
    ImageClassifierTask,
    MaskedLanguageModelTask,
    TextClassifierTask,
)

FP32 = Policy.fp32()


# --- abstract full-size configs (BASELINE.md configs[3], [4]) ------------


def test_imagenet_scale_classifier_shapes():
    """224×224×3 ImageInputAdapter, 512 latents, 6 layers (v5e-8)."""
    task = ImageClassifierTask(
        image_shape=(224, 224, 3), num_classes=1000,
        num_frequency_bands=64, num_latents=512,
        num_latent_channels=512, num_encoder_layers=6)
    model = task.build()
    params = jax.eval_shape(model.init, jax.random.key(0))

    def fwd(p, x):
        return model.apply(p, x, policy=FP32)

    x = jax.ShapeDtypeStruct((8, 224, 224, 3), jnp.float32)
    logits = jax.eval_shape(fwd, params, x)
    assert logits.shape == (8, 1000)
    # input tokens: 224·224 pixels, 3 + 2·(2·64+1) = 261 channels
    assert model.encoder.input_adapter.num_input_channels == 261


def test_perceiver_lm_scale_mlm_shapes():
    """1024×512 latents, 12 self-attn layers/block, seq 2048 (v5p-16)."""
    task = MaskedLanguageModelTask(
        vocab_size=32000, max_seq_len=2048, num_latents=1024,
        num_latent_channels=512,
        num_encoder_self_attention_layers_per_block=12,
        num_encoder_cross_attention_heads=8,
        num_encoder_self_attention_heads=8,
        num_decoder_cross_attention_heads=8)
    model = task.build()
    params = jax.eval_shape(model.init, jax.random.key(0))

    def fwd(p, ids, pad):
        logits, _ = model.apply(p, ids, pad, masking=False, policy=FP32)
        return logits

    ids = jax.ShapeDtypeStruct((4, 2048), jnp.int32)
    pad = jax.ShapeDtypeStruct((4, 2048), jnp.bool_)
    logits = jax.eval_shape(fwd, params, ids, pad)
    assert logits.shape == (4, 2048, 32000)
    n_params = sum(np.prod(l.shape) for l in jax.tree.leaves(params))
    assert n_params > 50e6  # genuinely LM-scale


# --- executed dp×tp steps on the virtual mesh ----------------------------


def _mlm_step(task, mesh, batch_size, seq_len, vocab):
    model = task.build()
    params = shard_params(model.init(jax.random.key(0)), mesh)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    bshard = batch_sharding(mesh)
    rng = np.random.default_rng(0)
    ids = jax.device_put(
        rng.integers(3, vocab, (batch_size, seq_len)).astype(np.int32),
        bshard)
    pad = jax.device_put(np.zeros((batch_size, seq_len), bool), bshard)

    @jax.jit
    def step(params, opt_state, ids, pad, key):
        def loss_fn(p):
            logits, labels = model.apply(p, ids, pad, rng=key,
                                         deterministic=False, policy=FP32)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            mask = labels != -100
            nll = -jnp.take_along_axis(
                logp, jnp.clip(labels, 0)[..., None], -1)[..., 0]
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    with mesh:
        params, opt_state, loss = step(params, opt_state, ids, pad,
                                       jax.random.key(1))
    return params, float(loss)


@pytest.mark.parametrize("tp", [2, 4])
def test_mlm_train_step_on_dp_tp_mesh(tp):
    """Reduced Perceiver-LM over (8/tp)×tp mesh: finite loss, and q/fc1
    weights really sharded over the model axis."""
    mesh = make_mesh(8, model_parallel=tp)
    # structure-faithful minimum: the assertions check sharding layout
    # and a finite loss, not capacity — depth/seq only pad the GSPMD
    # compile (test-suite budget, VERDICT r5 item 8)
    task = MaskedLanguageModelTask(
        vocab_size=256, max_seq_len=32, num_latents=8,
        num_latent_channels=32,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=4,
        num_encoder_self_attention_heads=4,
        num_decoder_cross_attention_heads=4)
    params, loss = _mlm_step(task, mesh, batch_size=mesh.shape["data"] * 2,
                             seq_len=32, vocab=256)
    assert np.isfinite(loss)

    def find_q(tree):
        if isinstance(tree, dict):
            if "q" in tree and isinstance(tree["q"], dict) \
                    and "w" in tree["q"]:
                return tree["q"]["w"]
            for v in tree.values():
                got = find_q(v)
                if got is not None:
                    return got
        return None

    qw = find_q(params)
    assert qw is not None
    spec = qw.sharding.spec
    assert "model" in tuple(spec), (
        f"q projection not tensor-parallel: spec={spec}")
    # per-device shard is 1/tp of the embed dim
    shard_shape = qw.sharding.shard_shape(qw.shape)
    assert shard_shape[-1] == qw.shape[-1] // tp


def test_text_classifier_dp8_step():
    """BASELINE configs[2]: seq_clf pure-DP over 8 devices."""
    mesh = make_mesh(8, model_parallel=1)
    task = TextClassifierTask(
        vocab_size=256, max_seq_len=32, num_latents=8,
        num_latent_channels=32)
    model = task.build()
    params = shard_params(model.init(jax.random.key(0)), mesh)
    bshard = batch_sharding(mesh)
    rng = np.random.default_rng(0)
    ids = jax.device_put(
        rng.integers(3, 256, (16, 32)).astype(np.int32), bshard)
    pad = jax.device_put(np.zeros((16, 32), bool), bshard)
    labels = jax.device_put(
        rng.integers(0, 2, (16,)).astype(np.int32), bshard)

    @jax.jit
    def loss_fn(p):
        logits = model.apply(p, ids, pad, policy=FP32)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(logp, labels[:, None], -1).mean()

    with mesh:
        loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    gnorm = optax.global_norm(grads)
    assert np.isfinite(float(gnorm)) and float(gnorm) > 0


def test_mlm_seq_parallel_matches_replicated():
    """pjit sequence parallelism: token axis sharded over a 'seq' mesh
    axis must give the same loss/gradients as the replicated run —
    GSPMD partitions the cross-attention kv axis and inserts the
    softmax collectives (the long-context path, BASELINE configs[4])."""
    from perceiver_tpu.parallel import seq_sharding

    task = MaskedLanguageModelTask(
        vocab_size=128, max_seq_len=64, num_latents=8,
        num_latent_channels=32,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=4,
        num_encoder_self_attention_heads=4,
        num_decoder_cross_attention_heads=4)
    model = task.build()
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    ids_np = rng.integers(3, 128, (4, 64)).astype(np.int32)
    pad_np = np.zeros((4, 64), bool)
    pad_np[:, 56:] = True  # exercise the masked-kv path across shards

    def loss_fn(p, ids, pad):
        logits, _ = model.apply(p, ids, pad, masking=False, policy=FP32)
        return (logits.astype(jnp.float32) ** 2).mean()

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(
        params, jnp.asarray(ids_np), jnp.asarray(pad_np))

    mesh = make_mesh(8, seq_parallel=4)
    assert mesh.shape == {"data": 2, "seq": 4, "model": 1}
    sp = seq_sharding(mesh)
    params_sharded = shard_params(params, mesh)
    ids = jax.device_put(ids_np, sp)
    pad = jax.device_put(pad_np, sp)
    with mesh:
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(
            params_sharded, ids, pad)

    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(ref_grads), jax.tree.leaves(grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_param_sharding_indivisible_dim_falls_back_to_replication():
    """A tensor-parallel spec on a dim the mesh axis doesn't divide
    (e.g. the (C, 10003) vocab projection over model=2) must fall back
    to replicating that dim instead of crashing device_put."""
    from jax.sharding import PartitionSpec as P

    from perceiver_tpu.parallel.sharding import param_sharding

    mesh = make_mesh(8, model_parallel=2)
    params = {
        "linear": {"w": jnp.zeros((64, 10003)),   # odd vocab: replicate
                   "b": jnp.zeros((10003,))},
        "fc1": {"w": jnp.zeros((64, 128)),        # divisible: sharded
                "b": jnp.zeros((128,))},
    }
    shardings = param_sharding(params, mesh)
    assert shardings["linear"]["w"].spec == P(None, None)
    assert shardings["fc1"]["w"].spec == P(None, "model")
    assert shardings["fc1"]["b"].spec == P("model")
    jax.device_put(params, shardings)  # must not raise
