#!/usr/bin/env python
"""Observability-plane smoke runner (docs/OBSERVABILITY.md).

Spins up a tiny REAL fleet (router + replica subprocesses) with
tracing on and a JSONL event directory armed, drives traffic through
a client-side :class:`~perceiver_tpu.serving.batcher.MicroBatcher` so
every request crosses every layer of the plane — client queue →
batch form → router route → RPC hop → replica admission → engine
dispatch → device materialize — then proves, in one process:

1. ``obs_trace_complete``: one request's trace, fetched from the live
   ``/traces/<id>`` endpoint, contains the full phase chain across at
   least two processes (client/router pid + replica pid), with the
   replica-side spans tagged by replica id;
2. ``obs_metrics_conformance``: the aggregated ``/metrics`` exposition
   parses and passes the Prometheus 0.0.4 conformance checks (every
   family typed, histogram buckets monotone, ``+Inf`` == ``_count``),
   with both replicas visible under the ``replica`` label next to the
   router's own ``fleet_*`` series;
3. ``obs_events_valid``: every line in every ``events-<pid>.jsonl``
   file validates against the shared event schema, and the files span
   multiple processes;
4. ``obs_zero_compiles``: the traffic run added ZERO XLA compiles on
   any replica (tracing is host-side only — the plane's budget gate);
5. ``obs_tracing_overhead``: recording a span and the disabled-path
   ``start_trace`` both stay under generous pinned bounds.

Emits one bench.py-format JSON line per check plus an ``obs_check``
summary; exits non-zero iff any check failed.  ``--fast`` shrinks the
traffic volume (tests/test_obs.py runs it as a tier-1 subprocess
gate)::

    JAX_PLATFORMS=cpu python scripts/obs_check.py --fast
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# tiny MLM task, mirroring the chaos fleet preset (scripts/chaos.py)
_TASK_KWARGS = dict(
    vocab_size=110, max_seq_len=32, num_latents=4,
    num_latent_channels=8, num_encoder_layers=1,
    num_encoder_self_attention_layers_per_block=1,
    num_encoder_cross_attention_heads=1,
    num_encoder_self_attention_heads=1,
    num_decoder_cross_attention_heads=1, loss_impl="dense")

_REQUIRED_PHASES = ("queue_wait", "batch_form", "route", "rpc_hop",
                    "pad_or_pack", "dispatch", "device")


def _publish_store(tmp: str):
    from perceiver_tpu.serving.graphs import build_serve_graph
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    from perceiver_tpu.training.checkpoint import ParamsVersionStore

    graph = build_serve_graph(MaskedLanguageModelTask(**_TASK_KWARGS))
    store = ParamsVersionStore(os.path.join(tmp, "store"))
    store.publish("v1", graph.init_params(0), set_current=True)
    return store


def _http_get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8")


def check_trace(obs_url: str, replies) -> dict:
    tids = [r.get("trace_id") for r in replies if isinstance(r, dict)]
    assert tids and all(tids), "replies carried no trace_id"
    status, body = _http_get(f"{obs_url}/traces/{tids[0]}")
    assert status == 200, status
    spans = json.loads(body)["spans"]
    phases = {s["phase"] for s in spans}
    missing = [p for p in _REQUIRED_PHASES if p not in phases]
    assert not missing, f"trace missing phases {missing}: {phases}"
    pids = {s["pid"] for s in spans}
    assert len(pids) >= 2, f"trace never crossed a process: {pids}"
    tagged = [s for s in spans
              if (s.get("attrs") or {}).get("replica")]
    assert tagged, "replica-side spans not tagged with the replica id"
    assert all(s["duration_s"] >= 0 for s in spans), spans
    return {"trace_id": tids[0], "spans": len(spans),
            "phases": sorted(phases), "processes": len(pids),
            "replica_tagged_spans": len(tagged),
            "traced_requests": len(tids)}


def check_metrics(obs_url: str) -> dict:
    from perceiver_tpu.obs import promparse

    status, text = _http_get(f"{obs_url}/metrics")
    assert status == 200, status
    problems = promparse.check_exposition(text)
    assert not problems, problems
    families = promparse.parse(text)
    replicas = {s.labels["replica"]
                for fam in families.values() for s in fam.samples
                if "replica" in s.labels}
    assert len(replicas) >= 2, f"replica label missing: {replicas}"
    # router-level series + a replica-level engine series must share
    # the one exposition (replicas expose engine metrics over RPC)
    for name in ("fleet_requests_total", "fleet_size",
                 "fleet_breaker_state", "serving_bucket_dispatch_total"):
        assert name in families, f"{name} not in the aggregated /metrics"
    status, body = _http_get(f"{obs_url}/healthz")
    assert status == 200, (status, body)
    return {"families": len(families),
            "samples": sum(len(f.samples) for f in families.values()),
            "replica_labels": sorted(replicas), "problems": problems}


def check_events(event_dir: str) -> dict:
    from perceiver_tpu.obs import events as events_mod

    files = sorted(glob.glob(os.path.join(event_dir, "events-*.jsonl")))
    assert len(files) >= 2, f"expected multi-process event files: {files}"
    counts: dict = {}
    total = 0
    for path in files:
        with open(path, encoding="utf-8") as f:
            for line in f:
                event = json.loads(line)
                events_mod.validate_event(event)  # raises on drift
                counts[event["type"]] = counts.get(event["type"], 0) + 1
                total += 1
    assert total > 0, "no events were logged"
    for etype in ("exec_cache", "health_transition"):
        assert etype in counts, f"no {etype} events: {sorted(counts)}"
    return {"files": len(files), "events": total, "by_type": counts}


def check_overhead() -> dict:
    from perceiver_tpu.obs import trace as trace_mod

    ctx = trace_mod.start_trace(origin="bench",
                                sink=trace_mod.SpanCollector())
    n = 5000
    t0 = time.perf_counter()
    for _ in range(n):
        ctx.record("dispatch", duration_s=0.0)
    per_span_us = (time.perf_counter() - t0) / n * 1e6
    trace_mod.set_enabled(False)
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            trace_mod.start_trace()
        disabled_us = (time.perf_counter() - t0) / n * 1e6
    finally:
        trace_mod.set_enabled(True)
    # generous pinned bounds: a span record is dict-building + a list
    # append; the disabled path is one module-global read
    assert per_span_us < 100.0, per_span_us
    assert disabled_us < 10.0, disabled_us
    return {"per_span_us": round(per_span_us, 3),
            "disabled_start_trace_us": round(disabled_us, 4),
            "iterations": n}


def run(tmp: str, *, requests: int) -> list:
    import numpy as np

    from perceiver_tpu.fleet import Fleet
    from perceiver_tpu.obs import events as events_mod
    from perceiver_tpu.obs import trace as trace_mod
    from perceiver_tpu.serving.batcher import MicroBatcher

    event_dir = os.path.join(tmp, "events")
    os.makedirs(event_dir, exist_ok=True)
    os.environ[events_mod.ENV_VAR] = event_dir
    events_mod.set_default_log(None)  # rebuild against the env dir
    os.environ.setdefault("PERCEIVER_EXEC_CACHE",
                          os.path.join(tmp, "exec_cache"))
    trace_mod.set_enabled(True)

    store = _publish_store(tmp)
    spec = {"task_class": "MaskedLanguageModelTask",
            "task_kwargs": _TASK_KWARGS,
            "batch_buckets": [4], "seq_buckets": [16],
            "store_dir": store.directory, "version": "v1", "seed": 0}
    results = []

    def record(metric, value, unit, detail):
        line = {"metric": metric, "value": value, "unit": unit,
                "vs_baseline": None, "detail": detail}
        results.append(line)
        print(json.dumps(line), flush=True)

    def gate(metric, unit, fn, *fn_args):
        try:
            detail = fn(*fn_args)
        except Exception as e:  # noqa: BLE001 — reported as a failed gate
            record(metric, 0.0, unit,
                   {"error": f"{type(e).__name__}: {e}"})
            return
        record(metric, 1.0, unit, detail)

    fleet = Fleet(spec, os.path.join(tmp, "fleet"), replicas=2,
                  dispatch_timeout_s=15.0)
    try:
        obs = fleet.start_obs()
        # post-warmup baseline: replica spin-up compiles (cold exec
        # cache) happen before this snapshot; traffic must add none
        compiles_before = {rid: s.get("compile_events")
                           for rid, s in fleet.statuses().items()}

        batcher = MicroBatcher(
            lambda payloads: [fleet.submit(p) for p in payloads],
            max_batch=4, max_delay_ms=2.0)
        rng = np.random.default_rng(0)
        futures = []
        for _ in range(requests):
            arrays = {"input_ids": rng.integers(
                          3, 110, (2, 16)).astype(np.int32),
                      "pad_mask": np.zeros((2, 16), bool)}
            futures.append(batcher.submit(arrays))
        replies = [f.result(timeout=120) for f in futures]
        compiles_after = {rid: s.get("compile_events")
                          for rid, s in fleet.statuses().items()}
        batcher.close()

        gate("obs_trace_complete", "ok", check_trace, obs.url, replies)
        gate("obs_metrics_conformance", "ok", check_metrics, obs.url)
        gate("obs_events_valid", "ok", check_events, event_dir)

        def zero_compiles():
            deltas = {rid: compiles_after.get(rid, -1)
                      - compiles_before.get(rid, 0)
                      for rid in compiles_before}
            assert all(d == 0 for d in deltas.values()), deltas
            return {"requests": len(replies),
                    "post_warmup_compile_deltas": deltas,
                    "spin_up_compiles": compiles_before}

        gate("obs_zero_compiles", "ok", zero_compiles)
    finally:
        fleet.close()

    gate("obs_tracing_overhead", "ok", check_overhead)
    return results


def main() -> int:
    ap = argparse.ArgumentParser(
        description="observability plane smoke runner")
    ap.add_argument("--fast", action="store_true",
                    help="tier-1 sized traffic volume")
    ap.add_argument("--requests", type=int, default=None,
                    help="override the traffic volume")
    ap.add_argument("--out", default=None,
                    help="also append the result lines to this path")
    args = ap.parse_args()
    requests = args.requests or (8 if args.fast else 24)

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory(prefix="obs-check-") as tmp:
        results = run(tmp, requests=requests)
    passed = sum(1 for r in results if r["value"] == 1.0)
    summary = {"metric": "obs_check",
               "value": round(passed / max(len(results), 1), 3),
               "unit": "fraction_passed", "vs_baseline": None,
               "detail": {"checks": len(results), "passed": passed,
                          "requests": requests, "fast": bool(args.fast),
                          "wall_s": round(time.perf_counter() - t0, 2)}}
    results.append(summary)
    print(json.dumps(summary), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            for line in results:
                f.write(json.dumps(line) + "\n")
    return 0 if passed == len(results) - 1 else 1


if __name__ == "__main__":
    sys.exit(main())
