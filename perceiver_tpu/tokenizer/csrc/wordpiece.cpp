// WordPiece tokenizer native core.
//
// The reference delegates tokenization to the Rust HF `tokenizers`
// library (reference perceiver/tokenizer.py:3-7); this is the
// framework's C++ equivalent for the two hot paths:
//
//   wp_encode_words — greedy longest-match WordPiece over a vocab hash
//     (byte-wise longest match; vocab entries are valid UTF-8, so
//     mid-codepoint splits can never match and char-boundary semantics
//     are preserved).
//   wp_train — count-scored pair-merge training (the HF
//     WordPieceTrainer algorithm: it wraps BpeTrainer, so merges are
//     selected by highest raw pair count) with incremental pair
//     bookkeeping, so training the IMDB corpus to a 10k vocab is
//     minutes of C++, not hours of Python.
//
// Normalization (NFD/lowercase/strip-accents) stays in Python: CPython's
// unicodedata is already a C extension and it is not on the hot path.
//
// Exposed over a plain C ABI for ctypes (no pybind11 in this image).
// Tie-breaking matches the pure-Python trainer exactly (count desc,
// then lowest (vocab_rank_a, vocab_rank_b)), so native and fallback
// engines produce identical vocabularies.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<int32_t, int32_t>& p) const {
        return std::hash<int64_t>()(
            (static_cast<int64_t>(p.first) << 32) ^
            static_cast<uint32_t>(p.second));
    }
};

struct Vocab {
    std::unordered_map<std::string, int32_t> token_to_id;
    size_t max_token_bytes = 0;
};

size_t utf8_len(const std::string& s) {
    size_t n = 0;
    for (unsigned char c : s)
        if ((c & 0xC0) != 0x80) ++n;
    return n;
}

}  // namespace

extern "C" {

void* wp_vocab_create(const char** tokens, int32_t n) {
    auto* v = new Vocab();
    for (int32_t i = 0; i < n; ++i) {
        std::string t(tokens[i]);
        v->max_token_bytes = std::max(v->max_token_bytes, t.size());
        v->token_to_id.emplace(std::move(t), i);
    }
    return v;
}

void wp_vocab_free(void* v) { delete static_cast<Vocab*>(v); }

// Length-aware core so batch callers can pass words containing any
// byte (including NUL — a c-string round-trip would truncate them and
// silently diverge from the pure-Python engine).
static int32_t encode_word_impl(const Vocab& v, const std::string& w,
                                int32_t unk_id, int32_t max_chars,
                                const std::string& pref,
                                int32_t* out, int32_t cap);

// Encode one pre-tokenized word. Appends piece ids to out (capacity cap);
// returns the number of ids written, or -1 if cap was insufficient.
int32_t wp_encode_word(void* vp, const char* word, int32_t unk_id,
                       int32_t max_chars, const char* prefix,
                       int32_t* out, int32_t cap) {
    return encode_word_impl(*static_cast<Vocab*>(vp), std::string(word),
                            unk_id, max_chars, std::string(prefix), out,
                            cap);
}

static int32_t encode_word_impl(const Vocab& v, const std::string& w,
                                int32_t unk_id, int32_t max_chars,
                                const std::string& pref,
                                int32_t* out, int32_t cap) {
    if (utf8_len(w) > static_cast<size_t>(max_chars)) {
        if (cap < 1) return -1;
        out[0] = unk_id;
        return 1;
    }
    int32_t count = 0;
    size_t start = 0;
    std::string candidate;
    while (start < w.size()) {
        size_t end = w.size();
        int32_t piece = -1;
        size_t piece_end = 0;
        while (start < end) {
            candidate.clear();
            if (start > 0) candidate = pref;
            candidate.append(w, start, end - start);
            auto it = v.token_to_id.find(candidate);
            if (it != v.token_to_id.end()) {
                piece = it->second;
                piece_end = end;
                break;
            }
            --end;
        }
        if (piece < 0) {
            if (cap < 1) return -1;
            out[0] = unk_id;
            return 1;
        }
        if (count >= cap) return -1;
        out[count++] = piece;
        start = piece_end;
    }
    return count;
}

// Encode a batch of pre-tokenized words, '\n'-joined, in one call —
// per-word FFI round-trips cost more than the WordPiece matching itself.
// Length-delimited (words may contain any byte except '\n', including
// NUL). Returns the number of ids written, or -1 if cap was
// insufficient.
int32_t wp_encode_words(void* vp, const char* words, int64_t words_len,
                        int32_t unk_id, int32_t max_chars,
                        const char* prefix, int32_t* out, int32_t cap) {
    const Vocab& v = *static_cast<Vocab*>(vp);
    const std::string pref(prefix);
    int32_t total = 0;
    const char* p = words;
    const char* end = words + words_len;
    std::string word;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        size_t len = static_cast<size_t>((nl ? nl : end) - p);
        word.assign(p, len);
        p = nl ? nl + 1 : end;
        if (word.empty()) continue;
        int32_t n = encode_word_impl(v, word, unk_id, max_chars, pref,
                                     out + total, cap - total);
        if (n < 0) return -1;
        total += n;
    }
    return total;
}

// Parallel document-batch encode into a padded (n_docs, max_len)
// row-major matrix. Each document is a '\n'-joined pre-tokenized word
// list spanning bytes [offsets[d], offsets[d+1]) of payload (length-
// delimited, so documents may be empty). Per doc, up to max_len ids
// are written to row d and lengths[d] reports how many — the stream is
// truncated at max_len, which matches truncate-after-encode semantics
// because WordPiece emits pieces strictly left to right. Rows are NOT
// cleared past lengths[d]; callers pre-fill the matrix with the pad
// id. Documents are split evenly across n_threads std::threads (the
// vocab hash is read-only); the Python caller drops the GIL for the
// duration of the call, so this is true multi-core tokenization.
void wp_encode_docs(void* vp, const char* payload, const int64_t* offsets,
                    int32_t n_docs, int32_t unk_id, int32_t max_chars,
                    const char* prefix, int32_t max_len,
                    int32_t* out, int32_t* lengths, int32_t n_threads) {
    if (n_threads < 1) n_threads = 1;
    n_threads = std::min(n_threads, std::max(n_docs, 1));

    auto work = [=](int32_t lo, int32_t hi) {
        const std::string pref(prefix);
        std::string word;
        std::vector<int32_t> scratch(
            static_cast<size_t>(max_len) + 256);
        for (int32_t d = lo; d < hi; ++d) {
            const char* p = payload + offsets[d];
            const char* end = payload + offsets[d + 1];
            int32_t* row = out + static_cast<int64_t>(d) * max_len;
            int32_t count = 0;
            while (p < end && count < max_len) {
                const char* nl = static_cast<const char*>(
                    memchr(p, '\n', static_cast<size_t>(end - p)));
                size_t len = static_cast<size_t>((nl ? nl : end) - p);
                word.assign(p, len);
                p = nl ? nl + 1 : end;
                if (word.empty()) continue;
                for (;;) {
                    int32_t n = encode_word_impl(
                        *static_cast<Vocab*>(vp), word, unk_id, max_chars,
                        pref, scratch.data(),
                        static_cast<int32_t>(scratch.size()));
                    if (n >= 0) {
                        int32_t take = std::min(n, max_len - count);
                        std::copy(scratch.begin(), scratch.begin() + take,
                                  row + count);
                        count += take;
                        break;
                    }
                    scratch.resize(scratch.size() * 2);
                }
            }
            lengths[d] = count;
        }
    };

    if (n_threads == 1) {
        work(0, n_docs);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    int32_t per = (n_docs + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        int32_t lo = t * per, hi = std::min(n_docs, lo + per);
        if (lo >= hi) break;
        pool.emplace_back(work, lo, hi);
    }
    for (auto& th : pool) th.join();
}

// Full-pipeline parallel encode for ASCII documents: added-special-token
// matching on the raw text, then per text segment literal Replaces →
// lowercase → HF-Whitespace word split (\w+|[^\w\s]+ with ASCII \w =
// [0-9A-Za-z_]) → WordPiece. On pure-ASCII input this is byte-exact
// with the Python chain (NFD and StripAccents are identities there);
// the Python caller routes non-ASCII documents through its own
// normalizer and marks them with offsets[d] == offsets[d+1] here.
// Output contract matches wp_encode_docs.
void wp_encode_docs_raw(void* vp, const char* payload,
                        const int64_t* offsets, int32_t n_docs,
                        const char** find, const char** repl,
                        int32_t n_replaces, int32_t lowercase,
                        const char** special_toks,
                        const int32_t* special_ids, int32_t n_specials,
                        int32_t unk_id, int32_t max_chars,
                        const char* prefix, int32_t max_len,
                        int32_t* out, int32_t* lengths,
                        int32_t n_threads) {
    if (n_threads < 1) n_threads = 1;
    n_threads = std::min(n_threads, std::max(n_docs, 1));

    std::vector<std::pair<std::string, std::string>> replaces;
    for (int32_t i = 0; i < n_replaces; ++i)
        replaces.emplace_back(find[i], repl[i]);
    std::vector<std::pair<std::string, int32_t>> specials;
    for (int32_t i = 0; i < n_specials; ++i)
        specials.emplace_back(special_toks[i], special_ids[i]);

    auto is_word = [](unsigned char c) {
        return (c >= '0' && c <= '9') || (c >= 'A' && c <= 'Z') ||
               (c >= 'a' && c <= 'z') || c == '_';
    };
    auto is_space = [](unsigned char c) {
        // Python's \s on ASCII: [ \t\n\r\f\v] plus the C0
        // separators \x1c-\x1f (FS/GS/RS/US)
        return c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
               c == '\f' || c == '\v' || (c >= 0x1c && c <= 0x1f);
    };

    auto work = [&, vp, unk_id, max_chars, max_len](int32_t lo,
                                                    int32_t hi) {
        const std::string pref(prefix);
        std::string seg, word;
        std::vector<int32_t> scratch(static_cast<size_t>(max_len) + 256);

        auto encode_word_into = [&](const std::string& w, int32_t* row,
                                    int32_t& count) {
            for (;;) {
                int32_t n = encode_word_impl(
                    *static_cast<Vocab*>(vp), w, unk_id, max_chars, pref,
                    scratch.data(), static_cast<int32_t>(scratch.size()));
                if (n >= 0) {
                    int32_t take = std::min(n, max_len - count);
                    std::copy(scratch.begin(), scratch.begin() + take,
                              row + count);
                    count += take;
                    return;
                }
                scratch.resize(scratch.size() * 2);
            }
        };

        // normalize one raw text segment and stream its pieces
        auto encode_segment = [&](const char* s, size_t len, int32_t* row,
                                  int32_t& count) {
            seg.assign(s, len);
            for (const auto& fr : replaces) {
                if (fr.first.empty()) continue;
                size_t pos = 0;
                while ((pos = seg.find(fr.first, pos))
                       != std::string::npos) {
                    seg.replace(pos, fr.first.size(), fr.second);
                    pos += fr.second.size();
                }
            }
            if (lowercase)
                for (char& c : seg)
                    if (c >= 'A' && c <= 'Z') c += 32;
            size_t i = 0;
            while (i < seg.size() && count < max_len) {
                unsigned char c = static_cast<unsigned char>(seg[i]);
                if (is_space(c)) { ++i; continue; }
                size_t j = i + 1;
                if (is_word(c)) {
                    while (j < seg.size() && is_word(
                            static_cast<unsigned char>(seg[j]))) ++j;
                } else {
                    while (j < seg.size()) {
                        unsigned char d = static_cast<unsigned char>(
                            seg[j]);
                        if (is_word(d) || is_space(d)) break;
                        ++j;
                    }
                }
                word.assign(seg, i, j - i);
                encode_word_into(word, row, count);
                i = j;
            }
        };

        for (int32_t d = lo; d < hi; ++d) {
            const char* p = payload + offsets[d];
            const char* end = payload + offsets[d + 1];
            int32_t* row = out + static_cast<int64_t>(d) * max_len;
            int32_t count = 0;
            const char* seg_start = p;
            while (p < end && count < max_len) {
                int32_t hit = -1;
                size_t hit_len = 0;
                for (size_t k = 0; k < specials.size(); ++k) {
                    const std::string& t = specials[k].first;
                    if (static_cast<size_t>(end - p) >= t.size() &&
                        memcmp(p, t.data(), t.size()) == 0) {
                        hit = static_cast<int32_t>(k);
                        hit_len = t.size();
                        break;
                    }
                }
                if (hit >= 0) {
                    if (p > seg_start)
                        encode_segment(seg_start,
                                       static_cast<size_t>(p - seg_start),
                                       row, count);
                    if (count < max_len)
                        row[count++] = specials[hit].second;
                    p += hit_len;
                    seg_start = p;
                } else {
                    ++p;
                }
            }
            if (seg_start < end && count < max_len)
                encode_segment(seg_start,
                               static_cast<size_t>(end - seg_start),
                               row, count);
            lengths[d] = count;
        }
    };

    if (n_threads == 1) {
        work(0, n_docs);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    int32_t per = (n_docs + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        int32_t lo = t * per, hi = std::min(n_docs, lo + per);
        if (lo >= hi) break;
        pool.emplace_back(work, lo, hi);
    }
    for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// Trainer
// ---------------------------------------------------------------------------

namespace {

struct Trainer {
    std::vector<std::string> id_to_sym;          // symbol strings
    std::unordered_map<std::string, int32_t> sym_to_id;
    std::vector<int32_t> rank;                   // symbol -> vocab index
    std::vector<std::vector<int32_t>> words;     // word -> symbol ids
    std::vector<int64_t> counts;                 // word -> corpus count
    using Pair = std::pair<int32_t, int32_t>;
    std::unordered_map<Pair, int64_t, PairHash> pair_freq;
    std::unordered_map<Pair, std::unordered_set<int32_t>, PairHash>
        pair_words;

    int32_t intern(const std::string& s) {
        auto it = sym_to_id.find(s);
        if (it != sym_to_id.end()) return it->second;
        int32_t id = static_cast<int32_t>(id_to_sym.size());
        id_to_sym.push_back(s);
        sym_to_id.emplace(s, id);
        rank.push_back(-1);
        return id;
    }

    void add_pairs_of(int32_t wi) {
        const auto& syms = words[wi];
        int64_t c = counts[wi];
        for (size_t j = 0; j + 1 < syms.size(); ++j) {
            Pair p{syms[j], syms[j + 1]};
            pair_freq[p] += c;
            pair_words[p].insert(wi);
        }
    }

    void remove_pairs_of(int32_t wi) {
        const auto& syms = words[wi];
        int64_t c = counts[wi];
        for (size_t j = 0; j + 1 < syms.size(); ++j) {
            Pair p{syms[j], syms[j + 1]};
            auto it = pair_freq.find(p);
            if (it != pair_freq.end()) {
                it->second -= c;
                if (it->second <= 0) {
                    pair_freq.erase(it);
                    pair_words.erase(p);
                }
            }
        }
    }
};

}  // namespace

// Train from unique words + counts (HF WordPieceTrainer algorithm:
// BPE count-scored merges with a continuation prefix — HF's trainer
// wraps BpeTrainer, so merges are selected by highest raw pair count,
// ties broken by lowest (vocab_rank_a, vocab_rank_b)). Returns a
// malloc'd buffer of '\n'-joined vocab tokens in id order (caller
// frees with wp_free).
char* wp_train(const char** word_strs, const int64_t* word_counts,
               int32_t n_words, const char** specials, int32_t n_specials,
               const char* prefix, int32_t vocab_size, int64_t min_freq) {
    Trainer tr;
    const std::string pref(prefix);

    // vocab under construction: specials, then the plain-char alphabet
    // sorted by codepoint (bytewise UTF-8 order == codepoint order),
    // then ##-continuation forms in word order, then merges — the HF
    // BpeTrainer vocab layout
    std::vector<std::string> vocab;
    std::unordered_set<std::string> vocab_set;
    auto add_vocab = [&](const std::string& t) -> int32_t {
        if (vocab_set.insert(t).second) {
            vocab.push_back(t);
            return static_cast<int32_t>(vocab.size()) - 1;
        }
        return -1;
    };
    for (int32_t i = 0; i < n_specials; ++i) add_vocab(specials[i]);

    // split words into UTF-8 chars once; collect the plain alphabet
    std::set<std::string> alphabet;
    std::vector<std::vector<std::string>> word_chars(n_words);
    tr.words.resize(n_words);
    tr.counts.assign(word_counts, word_counts + n_words);
    for (int32_t wi = 0; wi < n_words; ++wi) {
        const std::string w(word_strs[wi]);
        auto& chars = word_chars[wi];
        size_t i = 0;
        while (i < w.size()) {
            size_t j = i + 1;
            while (j < w.size() && (static_cast<unsigned char>(w[j]) & 0xC0)
                       == 0x80)
                ++j;
            chars.push_back(w.substr(i, j - i));
            alphabet.insert(chars.back());
            i = j;
        }
    }
    auto set_rank = [&](int32_t id, int32_t pos) {
        if (pos >= 0) tr.rank[id] = pos;
    };
    for (const auto& c : alphabet) {
        int32_t id = tr.intern(c);
        set_rank(id, add_vocab(c));
    }
    // tokenize words (first char plain, rest ##'d); unseen ## forms
    // join the vocab here, in word order
    for (int32_t wi = 0; wi < n_words; ++wi) {
        auto& syms = tr.words[wi];
        const auto& chars = word_chars[wi];
        for (size_t k = 0; k < chars.size(); ++k) {
            std::string s = k == 0 ? chars[k] : pref + chars[k];
            int32_t id = tr.intern(s);
            set_rank(id, add_vocab(s));
            syms.push_back(id);
        }
    }
    for (int32_t wi = 0; wi < n_words; ++wi) tr.add_pairs_of(wi);

    const int64_t effective_min = min_freq > 1 ? min_freq : 1;
    while (static_cast<int32_t>(vocab.size()) < vocab_size &&
           !tr.pair_freq.empty()) {
        // argmax pair count; tie → lowest (rank_a, rank_b)
        Trainer::Pair best{-1, -1};
        int64_t best_count = 0;
        for (const auto& kv : tr.pair_freq) {
            if (kv.second < effective_min) continue;
            bool better = kv.second > best_count;
            if (!better && kv.second == best_count && best.first >= 0) {
                int32_t ra1 = tr.rank[kv.first.first];
                int32_t rb1 = tr.rank[kv.first.second];
                int32_t ra0 = tr.rank[best.first];
                int32_t rb0 = tr.rank[best.second];
                better = ra1 < ra0 || (ra1 == ra0 && rb1 < rb0);
            }
            if (better) {
                best = kv.first;
                best_count = kv.second;
            }
        }
        if (best.first < 0) break;

        const std::string& a = tr.id_to_sym[best.first];
        const std::string& b = tr.id_to_sym[best.second];
        std::string merged = a + (b.rfind(pref, 0) == 0
                                  ? b.substr(pref.size()) : b);
        int32_t merged_id = tr.intern(merged);
        set_rank(merged_id, add_vocab(merged));

        // rewrite only the words containing the merged pair
        auto affected_it = tr.pair_words.find(best);
        if (affected_it == tr.pair_words.end()) break;
        std::vector<int32_t> affected(affected_it->second.begin(),
                                      affected_it->second.end());
        for (int32_t wi : affected) {
            tr.remove_pairs_of(wi);
            auto& syms = tr.words[wi];
            std::vector<int32_t> out;
            out.reserve(syms.size());
            size_t j = 0;
            while (j < syms.size()) {
                if (j + 1 < syms.size() && syms[j] == best.first &&
                    syms[j + 1] == best.second) {
                    out.push_back(merged_id);
                    j += 2;
                } else {
                    out.push_back(syms[j]);
                    ++j;
                }
            }
            syms.swap(out);
            tr.add_pairs_of(wi);
        }
    }

    size_t total = 0;
    for (const auto& t : vocab) total += t.size() + 1;
    char* buf = static_cast<char*>(malloc(total + 1));
    char* p = buf;
    for (const auto& t : vocab) {
        memcpy(p, t.data(), t.size());
        p += t.size();
        *p++ = '\n';
    }
    *p = '\0';
    return buf;
}

void wp_free(char* p) { free(p); }

}  // extern "C"
