"""Checkpoint save/restore on orbax (SURVEY §5 checkpoint/resume).

Covers the reference's three mechanisms:

1. Best-k retention monitored on ``val_loss`` (Lightning
   ``ModelCheckpoint``, ``trainer.yaml:10-14``) with hparams embedded —
   ``CheckpointHook``.
2. Cross-task transfer restore (``lightning.py:144-149``):
   ``restore_params(path)`` loads a checkpoint's params pytree so a
   task can graft the encoder subtree or the whole model.
3. Manual one-shot save/load (``run.py:278-281``): ``save_params``.

Orbax writes are async-capable and multi-host-safe (each host writes
its shard), which is the TPU-native answer to preemption: frequent
cheap checkpoints instead of elastic recovery (the reference has none
either, SURVEY §5 failure detection).

Integrity (docs/RESILIENCE.md): every completed save is sealed with an
atomically-written ``manifest.sha256.json`` (per-file sha256 + size)
inside the step directory. Restore verifies the newest step against
its manifest and *falls back* to the newest verified step instead of
crashing on a truncated/corrupt blob; a step with no manifest (a
pre-manifest checkpoint, or a crash in the narrow window between
orbax's atomic commit and the manifest write) is treated as legacy —
restorable, but ranked like any other step. If every step is provably
corrupt the restore raises a typed :class:`CheckpointIntegrityError`
(deliberately NOT a ``ValueError``/``KeyError`` so the trainer's
optimizer-mismatch degrade path never mistakes corruption for a
config change). Retention stays bounded by orbax's ``max_to_keep``
GC; manifests live inside the step dirs and are collected with them.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from perceiver_tpu.obs import events as events_mod
from perceiver_tpu.resilience import faults
from perceiver_tpu.training.state import TrainState

MANIFEST_NAME = "manifest.sha256.json"

#: verify() results
VERIFIED = "verified"
CORRUPT = "corrupt"
UNVERIFIED = "unverified"  # no manifest (legacy / crash window)


class CheckpointIntegrityError(RuntimeError):
    """Every candidate checkpoint step failed manifest verification."""


def _abs(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _manifest_files(step_dir: str):
    """Relative paths of every file under a committed step dir,
    excluding the manifest itself."""
    out = []
    for root, dirs, files in os.walk(step_dir):
        dirs.sort()
        for name in sorted(files):
            rel = os.path.relpath(os.path.join(root, name), step_dir)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return out


def write_manifest(step_dir: str) -> Dict[str, Any]:
    """Seal a committed checkpoint step: hash every file and publish
    the manifest atomically (tempfile + rename — a crash mid-write
    leaves the step unverified, never half-verified)."""
    files = {}
    for rel in _manifest_files(step_dir):
        path = os.path.join(step_dir, rel)
        files[rel] = {"sha256": _sha256_file(path),
                      "size": os.path.getsize(path)}
    manifest = {"version": 1, "files": files}
    tmp = os.path.join(step_dir, f".{MANIFEST_NAME}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, os.path.join(step_dir, MANIFEST_NAME))
    events_mod.emit("checkpoint_seal", path=step_dir)
    return manifest


def verify_step(step_dir: str) -> str:
    """``VERIFIED`` | ``CORRUPT`` | ``UNVERIFIED`` (no manifest).
    Corrupt = a listed file is missing, resized, or hash-mismatched,
    or the manifest itself is unreadable."""
    manifest_path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        return UNVERIFIED
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        for rel, want in manifest["files"].items():
            path = os.path.join(step_dir, rel)
            if not os.path.isfile(path) \
                    or os.path.getsize(path) != want["size"] \
                    or _sha256_file(path) != want["sha256"]:
                return CORRUPT
    except (OSError, ValueError, KeyError, TypeError):
        return CORRUPT  # unreadable manifest = unverifiable = corrupt
    return VERIFIED


def _truncate_one_blob(step_dir: str) -> None:
    """``ckpt.truncate`` fault: halve the largest data file in the
    step dir — post-commit corruption the manifest must catch."""
    best, best_size = None, -1
    for rel in _manifest_files(step_dir):
        size = os.path.getsize(os.path.join(step_dir, rel))
        if size > best_size:
            best, best_size = rel, size
    if best is not None:
        with open(os.path.join(step_dir, best), "r+b") as f:
            f.truncate(max(best_size // 2, 1))


class CheckpointHook:
    """val_loss-monitored best-k checkpointing of the full TrainState."""

    def __init__(self, directory: str, max_to_keep: int = 1,
                 monitor: str = "val_loss", mode: str = "min",
                 hparams: Optional[dict] = None,
                 enable_async: bool = True):
        self.directory = _abs(directory)
        self.monitor = monitor
        best_fn = (lambda m: m[monitor]) if monitor else None
        # enable_async=False forces the whole write (and the manifest
        # seal) to complete inside save(). Guard anchors NEED this: the
        # train step donates the TrainState, and on backends where
        # donation reuses the host buffer in place (CPU) an async save
        # serializes whatever the buffer holds when the writer drains —
        # a LATER step's state under the anchor's step label.
        self._async = enable_async
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=best_fn,
                best_mode=mode,
                enable_async_checkpointing=enable_async))
        # step whose async save has been issued but whose integrity
        # manifest is not written yet (sealed on the next save/wait)
        self._pending_manifest: Optional[int] = None
        if hparams is not None:
            os.makedirs(self.directory, exist_ok=True)
            with open(os.path.join(self.directory, "hparams.json"),
                      "w") as f:
                json.dump(hparams, f, indent=2, default=str)

    def save(self, step: int, state: TrainState, metrics: dict):
        metrics = {k: float(v) for k, v in metrics.items()}
        self._finalize_pending()
        self._mgr.save(step, args=ocp.args.StandardSave(
            {"params": state.params, "opt_state": state.opt_state,
             "rng": jax.random.key_data(state.rng), "step": state.step}),
            metrics=metrics)
        # crash-only checkpoint chaos: a SIGKILL here lands while the
        # async write/commit is in flight (tests/test_resilience.py)
        faults.maybe_kill("ckpt.kill_during_save")
        self._pending_manifest = step
        if not self._async:
            # synchronous mode: the write already committed — seal it
            # now so the newest anchor is always sha256-verified
            self._finalize_pending()

    def _finalize_pending(self) -> None:
        """Seal the previous async save with its integrity manifest
        (waits for it to commit first). Process 0 writes; every host
        verifies on restore."""
        step = self._pending_manifest
        if step is None:
            return
        self._mgr.wait_until_finished()
        self._pending_manifest = None
        step_dir = os.path.join(self.directory, str(step))
        if jax.process_index() == 0 and os.path.isdir(step_dir):
            write_manifest(step_dir)
            if faults.fire("ckpt.truncate"):
                _truncate_one_blob(step_dir)

    def _steps(self):
        """Committed step numbers on disk, newest first."""
        if not os.path.isdir(self.directory):
            return []
        return sorted((int(d) for d in os.listdir(self.directory)
                       if d.isdigit()), reverse=True)

    def verify(self, step: int) -> str:
        return verify_step(os.path.join(self.directory, str(step)))

    def _newest_restorable_step(self) -> Optional[int]:
        """Newest step that is not provably corrupt. Corrupt steps are
        skipped with a warning; if steps exist but all are corrupt,
        raise the typed integrity error."""
        steps = self._steps()
        for step in steps:
            status = self.verify(step)
            if status == CORRUPT:
                warnings.warn(
                    f"checkpoint step {step} in {self.directory} fails "
                    f"sha256 manifest verification — skipping it and "
                    f"falling back to the newest verified checkpoint",
                    stacklevel=3)
                continue
            return step
        if steps:
            raise CheckpointIntegrityError(
                f"every checkpoint step in {self.directory} "
                f"({steps}) fails manifest verification")
        return None

    def newest_restorable_step(self) -> Optional[int]:
        """Public face of the verified-newest-step scan: the step a
        ``restore_latest`` would load, or ``None``. The multi-host
        chaos harness uses it to assert a re-formed group resumed from
        exactly the anchor the killed generation left behind."""
        return self._newest_restorable_step()

    def restore_latest(self, template_state: TrainState
                       ) -> Optional[TrainState]:
        step = self._newest_restorable_step()
        if step is None:
            return None
        return self.restore(step, template_state)

    def restore_params_and_step(self, template_state: TrainState
                                ) -> Optional[TrainState]:
        """Partial resume for a checkpoint whose optimizer state no
        longer matches the current optimizer/scheduler config (e.g.
        the schedule was changed between runs): restore params + rng +
        step, keep the template's freshly initialized opt_state."""
        step = self._newest_restorable_step()
        if step is None:
            return None
        got = _partial_restore(
            os.path.join(self.directory, str(step), "default"),
            {"params": template_state.params,
             "rng": jax.random.key_data(template_state.rng),
             "step": template_state.step})
        return TrainState(params=got["params"],
                          opt_state=template_state.opt_state,
                          rng=jax.random.wrap_key_data(got["rng"]),
                          step=got["step"])

    def restore(self, step: int, template_state: TrainState) -> TrainState:
        template = {
            "params": template_state.params,
            "opt_state": template_state.opt_state,
            "rng": jax.random.key_data(template_state.rng),
            "step": template_state.step,
        }
        got = self._mgr.restore(step,
                                args=ocp.args.StandardRestore(template))
        return TrainState(params=got["params"],
                          opt_state=got["opt_state"],
                          rng=jax.random.wrap_key_data(got["rng"]),
                          step=got["step"])

    def wait(self):
        self._mgr.wait_until_finished()
        self._finalize_pending()

    def close(self):
        self._finalize_pending()
        self._mgr.close()


def save_params(path: str, params: Any, hparams: Optional[dict] = None):
    """One-shot params save (the ``run.py:278-281`` analogue).
    Overwrites like ``torch.save`` — a rerun into the same directory
    must not crash at the end of training."""
    path = _abs(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "params"), params, force=True)
    if hparams is not None:
        with open(os.path.join(path, "hparams.json"), "w") as f:
            json.dump(hparams, f, indent=2, default=str)


def _partial_restore(path: str, item: dict) -> dict:
    """Typed partial restore of selected subtrees from a checkpoint
    step's ``default`` item dir (a save may hold more than the caller
    wants — or can type — e.g. an opt_state from a different optimizer
    config).

    Orbax's native ``partial_restore`` kwarg only exists from the 0.9
    line; this image ships 0.7, where the supported spelling of "drop
    checkpoint subtrees absent from my template" is an empty
    ``transforms`` dict (fallback-to-item semantics). Try the modern
    kwarg first so an orbax upgrade keeps working, then degrade."""
    with ocp.PyTreeCheckpointer() as ckptr:
        restore_args = ocp.checkpoint_utils.construct_restore_args(item)
        try:
            args = ocp.args.PyTreeRestore(
                item=item, restore_args=restore_args,
                partial_restore=True)
        except TypeError:
            args = ocp.args.PyTreeRestore(
                item=item, restore_args=restore_args, transforms={})
        return ckptr.restore(path, args=args)


class ParamsVersionStore:
    """Versioned, sha256-sealed params directory for fleet rollouts.

    Layout: ``<dir>/<version>/params/...`` (a ``save_params`` tree)
    sealed by the same ``manifest.sha256.json`` as training
    checkpoints, plus an atomically-replaced ``CURRENT`` pointer file.
    The rolling-update protocol (docs/SERVING.md "Fleet") loads a
    version only after :meth:`verify` returns ``VERIFIED`` — a blob
    that rotted (or was corrupted mid-publish) raises the same typed
    :class:`CheckpointIntegrityError` the trainer uses, which the
    rollout turns into an auto-rollback.
    """

    CURRENT_NAME = "CURRENT"

    def __init__(self, directory: str):
        self.directory = _abs(directory)
        # the CURRENT pointer state lives on disk, so there is no
        # _GUARDED attr to declare — but two threads of ONE process
        # share the pid-suffixed temp name, so the write-then-replace
        # in set_current needs in-process serialization (cross-process
        # writers already each get their own pid)
        self._lock = threading.Lock()
        os.makedirs(self.directory, exist_ok=True)

    # -- publishing -------------------------------------------------------

    def publish(self, version: str, params: Any,
                *, set_current: bool = True) -> str:
        """Write ``params`` as ``version``, seal it with a manifest,
        and (by default) flip the CURRENT pointer. Returns the version
        directory. Re-publishing an existing version is an error —
        versions are immutable once sealed."""
        if not version or os.sep in version or version == self.CURRENT_NAME:
            raise ValueError(f"bad version name {version!r}")
        vdir = self.path(version)
        if os.path.exists(vdir):
            raise FileExistsError(f"version {version!r} already published")
        save_params(vdir, params)
        write_manifest(vdir)
        if set_current:
            self.set_current(version)
        return vdir

    def set_current(self, version: str) -> None:
        """Atomically repoint CURRENT (tempfile + ``os.replace`` — a
        crash leaves the old pointer, never a torn one). Serialized
        in-process: concurrent callers share the pid-suffixed temp
        name, and an unserialized pair can os.replace the temp file
        out from under a sibling mid-write."""
        if version not in self.versions():
            raise FileNotFoundError(f"unknown version {version!r}")
        tmp = os.path.join(self.directory,
                           f".{self.CURRENT_NAME}.tmp.{os.getpid()}")
        with self._lock:
            with open(tmp, "w") as f:
                f.write(version + "\n")
            os.replace(tmp,
                       os.path.join(self.directory, self.CURRENT_NAME))

    # -- reading ----------------------------------------------------------

    def path(self, version: str) -> str:
        return os.path.join(self.directory, version)

    def versions(self):
        """Published version names, sorted."""
        return sorted(
            d for d in os.listdir(self.directory)
            if os.path.isdir(self.path(d)) and not d.startswith("."))

    def current(self) -> Optional[str]:
        try:
            with open(os.path.join(self.directory, self.CURRENT_NAME)) as f:
                version = f.read().strip()
        except OSError:
            return None
        return version or None

    def verify(self, version: str) -> str:
        """``VERIFIED`` | ``CORRUPT`` | ``UNVERIFIED`` for one version."""
        return verify_step(self.path(version))

    def load(self, version: str, template: Any = None) -> Any:
        """Verified load: raises :class:`CheckpointIntegrityError` if
        the version's manifest check fails, so a replica can never
        swap in rotted params mid-rollout."""
        status = self.verify(version)
        if status == CORRUPT:
            raise CheckpointIntegrityError(
                f"params version {version!r} in {self.directory} fails "
                f"sha256 manifest verification")
        return restore_params(self.path(version), template)


def restore_params(path: str, template: Any = None) -> Any:
    """Load a params pytree from either a ``save_params`` directory or a
    ``CheckpointHook`` step directory (transfer-learning source,
    ``lightning.py:144-149``). ``template`` (a params pytree) pins
    shapes/dtypes for a safe typed restore; without it orbax falls back
    to the on-disk metadata."""
    path = _abs(path)
    # (checkpoint dir, template shape): save_params stores the bare
    # params tree; CheckpointHook steps store {params, opt_state, ...}
    # — only params is restored from those (partial restore)
    candidates = [(os.path.join(path, "params"), False)]
    if os.path.isdir(path):
        # CheckpointHook layout: <dir>/<step>/default/... → pick best/latest
        steps = sorted(int(d) for d in os.listdir(path) if d.isdigit())
        candidates += [(os.path.join(path, str(s), "default"), True)
                       for s in reversed(steps)]
    for c, wrapped in candidates:
        if not os.path.isdir(c):
            continue
        if wrapped and verify_step(os.path.dirname(c)) == CORRUPT:
            # serving-side verified restore: never load a step whose
            # manifest proves its blobs rotted (docs/RESILIENCE.md)
            warnings.warn(f"skipping corrupt checkpoint step "
                          f"{os.path.dirname(c)}", stacklevel=2)
            continue
        if template is not None and wrapped:
            # hook layout stores {params, opt_state, rng, step}; only
            # params is wanted (and only its template is available)
            got = _partial_restore(c, {"params": template})
        else:
            with ocp.StandardCheckpointer() as ckptr:
                got = ckptr.restore(c, template)
        return got.get("params", got) if isinstance(got, dict) \
            else got
    raise FileNotFoundError(f"No checkpoint found under {path}")


class MultiModelStore:
    """Directory of per-model :class:`ParamsVersionStore` substores.

    Layout: ``<dir>/<model>/<version>/params/...`` — each model id owns
    an independent sealed-version directory with its own ``CURRENT``
    pointer, so per-tenant rolling updates (docs/SERVING.md
    "Multi-tenancy") stage/commit one model's version without touching
    any other model's pointer. Model ids share the version-name rules
    (no separators, not ``CURRENT``); substores are created lazily on
    first reference and cached.
    """

    # lock discipline (gated by check.py --race): the substore cache is
    # populated lazily from replica dispatch threads and the rollout
    # driver concurrently
    _GUARDED = {"_stores": "_lock"}

    def __init__(self, directory: str):
        self.directory = _abs(directory)
        self._lock = threading.Lock()
        self._stores: Dict[str, ParamsVersionStore] = {}
        os.makedirs(self.directory, exist_ok=True)

    def model(self, model_id: str) -> ParamsVersionStore:
        """The (lazily created) version store for ``model_id``."""
        if not model_id or os.sep in model_id \
                or model_id == ParamsVersionStore.CURRENT_NAME \
                or model_id.startswith("."):
            raise ValueError(f"bad model id {model_id!r}")
        with self._lock:
            store = self._stores.get(model_id)
            if store is None:
                store = ParamsVersionStore(
                    os.path.join(self.directory, model_id))
                self._stores[model_id] = store
            return store

    def models(self):
        """Model ids with an on-disk substore, sorted (lazily created
        but still-empty substores count — they have a directory)."""
        return sorted(
            d for d in os.listdir(self.directory)
            if os.path.isdir(os.path.join(self.directory, d))
            and not d.startswith("."))
