"""LArTPC wire-image event source behind a Dataset seam.

Parity target: reference ``run.py:29-70`` (``LArCVDataset``), which
reads 512×512 wire images + per-pixel labels from ROOT files through
the larcv ``IOManager`` (a C++ physics-I/O stack). That stack is an
optional site dependency, so the seam here accepts three sources, all
yielding the same ``ArrayDataset(image=(N,H,W) f32, label=(N,H,W) i32)``:

1. larcv ROOT files, when the ``larcv`` package is importable
   (plane-2 "wire"/"label" Image2D products, as ``run.py:53-60``);
2. NPZ files with raw ``image``/``label`` arrays (the portable
   interchange format — convert once on a machine that has larcv);
3. a synthetic track/shower generator for smoke tests and benchmarks.

Behavior reproduced from the reference:

- negative wire values clamped to 0 (``run.py:57``);
- raw label remap to 3 classes — shift non-negative labels up by one,
  send negatives to background, then fold {2}→1 and {≥3}→2
  (``run.py:62-65``);
- events kept only if they have more than ``min_pixels`` nonzero
  pixels — 2621 at 512×512, i.e. 1% occupancy (``run.py:121-126``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from perceiver_tpu.data.core import ArrayDataset

MIN_PIXELS_512 = 2621  # reference run.py:125 (≈1% of 512²)


def remap_labels(raw: np.ndarray) -> np.ndarray:
    """Raw larcv 5-label scheme → 3 classes (run.py:62-65)."""
    lbl = raw.astype(np.int64).copy()
    lbl[raw >= 0] += 1
    lbl[raw < 0] = 0
    lbl[lbl == 2] = 1
    lbl[lbl >= 3] = 2
    return lbl.astype(np.int32)


def min_pixels_for(size: int) -> int:
    """Occupancy threshold scaled from the reference's 512×512 value."""
    return max(1, int(MIN_PIXELS_512 * (size * size) / (512 * 512)))


def _filter_occupancy(images: np.ndarray, labels: np.ndarray,
                      min_pixels: int):
    keep = (images > 0).sum(axis=(1, 2)) > min_pixels
    return images[keep], labels[keep]


def load_larcv_events(files: Sequence[str], size: int = 512,
                      plane: int = 2) -> ArrayDataset:
    """Read events via larcv IOManager (requires the larcv package)."""
    from larcv import larcv  # optional C++ site dependency

    io = larcv.IOManager(larcv.IOManager.kREAD, "io",
                         larcv.IOManager.kTickBackward)
    io.set_verbosity(5)
    for f in files:
        io.add_in_file(f)
    io.initialize()
    images, labels = [], []
    for idx in range(io.get_n_entries()):
        io.read_entry(idx)
        wire = io.get_data(larcv.kProductImage2D, "wire")
        img = larcv.as_ndarray(
            wire.Image2DArray()[plane].as_vector()).reshape(size, size)
        img = np.maximum(img, 0.0).astype(np.float32)
        ev_label = io.get_data(larcv.kProductImage2D, "label")
        raw = larcv.as_ndarray(
            ev_label.Image2DArray()[plane].as_vector()).reshape(size, size)
        images.append(img)
        labels.append(remap_labels(raw))
    return ArrayDataset(image=np.stack(images), label=np.stack(labels))


def load_npz_events(files: Sequence[str]) -> ArrayDataset:
    """NPZ interchange: ``image`` (N,H,W) float, ``label`` (N,H,W) raw
    larcv labels (remapped here) or pre-remapped if ``remapped=True``
    is stored."""
    images, labels = [], []
    for f in files:
        with np.load(f) as z:
            img = np.maximum(np.asarray(z["image"], np.float32), 0.0)
            raw = np.asarray(z["label"])
            already = "remapped" in z.files and bool(z["remapped"])
            lbl = raw.astype(np.int32) if already else remap_labels(raw)
            images.append(img)
            labels.append(lbl)
    return ArrayDataset(image=np.concatenate(images),
                        label=np.concatenate(labels))


def synthetic_events(num_events: int, size: int = 512,
                     seed: int = 0) -> ArrayDataset:
    """Track/shower-like events for smoke tests: straight MIP tracks
    (raw label 1 → class 1) and fuzzy EM-shower blobs (raw label 3 →
    class 2) on empty background (raw −1 → class 0)."""
    rng = np.random.default_rng(seed)
    images = np.zeros((num_events, size, size), np.float32)
    raw = -np.ones((num_events, size, size), np.int64)
    yy, xx = np.mgrid[0:size, 0:size]
    for i in range(num_events):
        for _ in range(rng.integers(2, 5)):  # tracks
            x0, y0 = rng.uniform(0, size, 2)
            ang = rng.uniform(0, np.pi)
            length = rng.uniform(0.3, 1.0) * size
            dx, dy = np.cos(ang), np.sin(ang)
            t = (xx - x0) * dx + (yy - y0) * dy
            dist = np.abs(-(xx - x0) * dy + (yy - y0) * dx)
            on = (dist < 1.5) & (t >= 0) & (t <= length)
            images[i][on] = rng.uniform(20, 100)
            raw[i][on] = 1
        for _ in range(rng.integers(1, 3)):  # showers
            cx, cy = rng.uniform(0.2 * size, 0.8 * size, 2)
            sigma = rng.uniform(0.02, 0.06) * size
            r2 = (xx - cx) ** 2 + (yy - cy) ** 2
            blob = rng.random((size, size)) < 0.5 * np.exp(
                -r2 / (2 * sigma ** 2))
            images[i][blob] = rng.uniform(10, 80)
            raw[i][blob] = 3
    return ArrayDataset(image=images, label=remap_labels(raw))


def load_lartpc(files: Optional[Sequence[str]] = None, size: int = 512,
                num_synthetic: int = 64, seed: int = 0,
                min_pixels: Optional[int] = None) -> ArrayDataset:
    """Resolve the best available source and apply the occupancy filter."""
    if files is not None and len(files) == 0:
        raise ValueError(
            "Empty file list: pass event files or omit --files entirely "
            "for the synthetic generator")
    if files:
        if all(str(f).endswith(".npz") for f in files):
            ds = load_npz_events(files)
        else:
            ds = load_larcv_events(files, size=size)
    else:
        ds = synthetic_events(num_synthetic, size=size, seed=seed)
    mp = min_pixels if min_pixels is not None else min_pixels_for(
        ds.fields["image"].shape[1])
    images, labels = _filter_occupancy(ds.fields["image"],
                                       ds.fields["label"], mp)
    return ArrayDataset(image=images, label=labels)
