"""Fleet-wide metrics aggregation.

Each replica already serves its engine's full registry over the
``metrics`` RPC op; the aggregator scrapes every live replica, parses
the expositions, and re-emits ONE exposition in which every replica
series carries a ``replica`` label — plus the router's own registry
(per-replica breaker state, retries, queue depths) appended verbatim,
since ``fleet_*`` names never collide with ``serving_*`` names.

A replica that fails its scrape (mid-restart, mid-kill) is skipped and
surfaced as ``fleet_scrape_errors_total`` rather than failing the
whole endpoint: the metrics plane must degrade, not flap, under
exactly the chaos it exists to observe.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

from perceiver_tpu.obs import promparse
from perceiver_tpu.serving.metrics import escape_label_value

__all__ = ["merge_expositions", "FleetAggregator"]


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    out = repr(float(v))
    return out[:-2] if out.endswith(".0") else out


def _fmt_sample(sample: promparse.Sample,
                extra: Optional[Tuple[str, str]] = None) -> str:
    labels = dict(sample.labels)
    if extra is not None:
        labels[extra[0]] = extra[1]
    if labels:
        inner = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in sorted(labels.items()))
        return f"{sample.name}{{{inner}}} {_fmt_value(sample.value)}"
    return f"{sample.name} {_fmt_value(sample.value)}"


def merge_expositions(per_source: Dict[str, str],
                      label: str = "replica",
                      extra_texts: Sequence[str] = ()) -> str:
    """Merge ``{source_id: exposition_text}`` into one exposition where
    every sample gains ``label="<source_id>"``; ``extra_texts`` (e.g.
    the router's own registry render) are appended with no relabeling.

    Raises :class:`promparse.ParseError` if any input is malformed —
    callers scrape our own emitter, so malformed input is a bug.
    """
    families: Dict[str, promparse.Family] = {}
    rendered: Dict[str, List[str]] = {}
    for source in sorted(per_source):
        for fam in promparse.parse(per_source[source]).values():
            known = families.get(fam.name)
            if known is None:
                families[fam.name] = fam
                rendered[fam.name] = []
            elif known.kind != fam.kind:
                raise promparse.ParseError(
                    f"{fam.name}: kind mismatch across sources "
                    f"({known.kind} vs {fam.kind})")
            rendered[fam.name].extend(
                _fmt_sample(s, (label, source)) for s in fam.samples)
    lines: List[str] = []
    for name in sorted(families):
        fam = families[name]
        if fam.help:
            lines.append(f"# HELP {name} {fam.help}")
        lines.append(f"# TYPE {name} {fam.kind}")
        lines.extend(rendered[name])
    for text in extra_texts:
        stripped = text.strip("\n")
        if stripped:
            lines.append(stripped)
    return "\n".join(lines) + "\n"


class FleetAggregator:
    """Scrape-and-merge view over a live :class:`fleet.supervisor.
    Fleet` — the callable behind the obs server's ``/metrics``."""

    def __init__(self, fleet) -> None:
        self._fleet = fleet
        m = fleet.router.metrics
        self._m_scrape_errors = m.counter(
            "fleet_scrape_errors_total",
            "replica metric scrapes that failed, by replica")

    def scrape(self) -> Dict[str, str]:
        """Per-replica exposition text, skipping unreachable replicas."""
        from perceiver_tpu.fleet.rpc import RpcError

        out: Dict[str, str] = {}
        for rid in self._fleet.supervisor.replicas():
            handle = self._fleet.supervisor.handle_of(rid)
            if handle is None:
                continue
            try:
                out[rid] = handle.metrics_text()
            except (RpcError, OSError):
                # a dying replica must not take /metrics down with it
                self._m_scrape_errors.labels(replica=rid).inc()
        return out

    def render(self) -> str:
        return merge_expositions(
            self.scrape(),
            extra_texts=(self._fleet.router.metrics.render(),))
