"""Masked-sample prediction — the framework's inference entry.

Parity target: reference ``perceiver/utils.py:22-43`` / SURVEY §3.5:
encode raw strings (containing ``[MASK]``) with the data collator, run
the MLM with ``masking=False``, take top-k vocab logits at each masked
position, substitute each of the k predictions, and decode back to k
complete strings per sample.
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_tpu.tokenizer import MASK_TOKEN_ID


def predict_masked_samples(masked_samples: List[str],
                           encode_fn: Callable,
                           tokenizer,
                           model,
                           params,
                           num_predictions: int = 3,
                           policy=None) -> List[List[str]]:
    ids, pad_mask = encode_fn(masked_samples)
    ids = jnp.asarray(ids)
    pad_mask = jnp.asarray(pad_mask)

    kwargs = {} if policy is None else {"policy": policy}
    logits, _ = jax.jit(
        lambda p, x, m: model.apply(p, x, m, masking=False, **kwargs)
    )(params, ids, pad_mask)

    ids = np.asarray(ids)
    _, top = jax.lax.top_k(logits.astype(jnp.float32), num_predictions)
    top = np.asarray(top)

    results: List[List[str]] = []
    for b in range(ids.shape[0]):
        mask_pos = np.nonzero(ids[b] == MASK_TOKEN_ID)[0]
        preds = []
        for k in range(num_predictions):
            filled = ids[b].copy()
            filled[mask_pos] = top[b, mask_pos, k]
            preds.append(tokenizer.decode(filled.tolist()))
        results.append(preds)
    return results
