"""Tests for the observability plane (perceiver_tpu/obs/).

Unit coverage for tracing, the event log, the exposition
parser/aggregator, the HTTP endpoint, and training telemetry; plus two
integration gates — ``scripts/obs_check.py --fast`` as a tier-1
subprocess (the check.py pattern) and the real-socket fleet proof that
a request whose replica is SIGKILLed mid-flight still yields ONE trace
with the failed hop, the retry, and the sibling's spans (slow).
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import pytest

from perceiver_tpu.obs import events as events_mod
from perceiver_tpu.obs import trace as trace_mod
from perceiver_tpu.obs.aggregate import merge_expositions
from perceiver_tpu.obs.events import EventLog, validate_event
from perceiver_tpu.obs import promparse
from perceiver_tpu.obs.server import ObsServer
from perceiver_tpu.obs.telemetry import Telemetry, install_signal_profiler
from perceiver_tpu.obs.trace import SpanCollector, TraceBuffer
from perceiver_tpu.serving.metrics import (
    MetricsRegistry,
    escape_label_value,
    unescape_label_value,
)

# --- tracing -----------------------------------------------------------------


def test_trace_phase_vocabulary_is_closed():
    ctx = trace_mod.start_trace(sink=SpanCollector())
    with pytest.raises(ValueError, match="unknown trace phase"):
        ctx.record("warmup")


def test_trace_span_shape_and_duration():
    sink = SpanCollector()
    ctx = trace_mod.start_trace(origin="router", sink=sink)
    span = ctx.record("dispatch", duration_s=0.5, bucket="b4_s16")
    assert span["trace_id"] == ctx.trace_id
    assert span["phase"] == "dispatch"
    assert span["duration_s"] == pytest.approx(0.5)
    assert span["pid"] == os.getpid()
    assert span["origin"] == "router"
    assert span["attrs"] == {"bucket": "b4_s16"}
    assert sink.spans == [span]


def test_trace_buffer_lru_eviction_and_span_bound():
    buf = TraceBuffer(max_traces=2, max_spans_per_trace=3)
    for tid in ("t0", "t1", "t2"):
        buf.add(tid, {"phase": "dispatch"})
    assert buf.get("t0") is None  # LRU-evicted
    assert set(buf.trace_ids()) == {"t1", "t2"}
    for _ in range(5):
        buf.add("t1", {"phase": "dispatch"})
    assert len(buf.get("t1")) == 3  # bounded per trace
    assert buf.dropped_spans == 3
    buf.get("t1")


def test_trace_wire_roundtrip_and_absorb_retags():
    parent_sink = SpanCollector()
    parent = trace_mod.start_trace(origin="router", sink=parent_sink)
    # replica side: rebuild from the RPC envelope, collect locally
    collector = SpanCollector()
    remote = trace_mod.from_wire(parent.wire(), sink=collector,
                                 origin="replica")
    assert remote.trace_id == parent.trace_id
    remote.record("queue_wait", duration_s=0.01)
    remote.record("device", duration_s=0.02)
    # router side: absorb the reply's spans, tagged with the replica id
    parent.absorb(collector.spans, replica="r1")
    absorbed = parent_sink.spans
    assert [s["phase"] for s in absorbed] == ["queue_wait", "device"]
    assert all(s["trace_id"] == parent.trace_id for s in absorbed)
    assert all(s["attrs"]["replica"] == "r1" for s in absorbed)
    # the replica's own copies were not mutated by the tagging
    assert "replica" not in (collector.spans[0].get("attrs") or {})


def test_trace_disabled_short_circuits():
    try:
        trace_mod.set_enabled(False)
        assert trace_mod.start_trace() is None
        assert trace_mod.from_wire({"trace_id": "abc"}) is None
    finally:
        trace_mod.set_enabled(True)


def test_trace_attach_region_records_into_all_members():
    sinks = [SpanCollector(), SpanCollector()]
    ctxs = [trace_mod.start_trace(sink=s) for s in sinks]
    with trace_mod.attach(ctxs + [None]):  # None members are dropped
        with trace_mod.region("pad_or_pack", bucket="b4"):
            pass
    for sink, ctx in zip(sinks, ctxs):
        (span,) = sink.spans
        assert span["phase"] == "pad_or_pack"
        assert span["trace_id"] == ctx.trace_id
        assert span["attrs"] == {"bucket": "b4"}
    # outside the attach block the region is a no-op
    with trace_mod.region("dispatch"):
        pass
    assert all(len(s.spans) == 1 for s in sinks)


def test_default_buffer_swap_restores():
    mine = TraceBuffer(max_traces=4)
    prev = trace_mod.set_default_buffer(mine)
    try:
        ctx = trace_mod.start_trace()
        ctx.record("submit", duration_s=0.0)
        assert mine.get(ctx.trace_id)
    finally:
        assert trace_mod.set_default_buffer(prev) is mine


# --- event log ---------------------------------------------------------------


def test_event_schema_validation():
    log = EventLog()
    event = log.emit("breaker_transition", bucket="b4_s16",
                     old="closed", new="open")
    validate_event(event)  # envelope + typed fields
    with pytest.raises(ValueError, match="unknown event type"):
        log.emit("reactor_meltdown")
    with pytest.raises(ValueError, match="missing required"):
        log.emit("guard_skip")  # no step
    with pytest.raises(ValueError, match="envelope"):
        validate_event({"type": "guard_skip", "step": 1})


def test_decode_phases_and_stream_events_in_vocabulary():
    """ISSUE 14: the decode plane speaks the closed observability
    vocabulary — per-token trace phases (``decode_step`` spans the
    batched device step, ``token_emit`` each stream's token delivery)
    and stream lifecycle events (``stream_open``/``stream_close``).
    A vocabulary miss would make DecodeEngine's tracing raise on the
    first admitted stream."""
    assert "decode_step" in trace_mod.PHASES
    assert "token_emit" in trace_mod.PHASES
    sink = SpanCollector()
    ctx = trace_mod.start_trace(origin="decode", sink=sink)
    ctx.record("decode_step", duration_s=0.001, live=3)
    ctx.record("token_emit", duration_s=0.0, stream="s1", index=0)
    assert [s["phase"] for s in sink.spans] == ["decode_step",
                                                "token_emit"]

    log = EventLog()
    validate_event(log.emit("stream_open", stream="s1", tenant="default"))
    validate_event(log.emit("stream_close", stream="s1", tokens=12,
                            tenant="default"))
    with pytest.raises(ValueError, match="missing required"):
        log.emit("stream_close", stream="s1", tenant="default")  # tokens
    assert [e["type"] for e in log.events()] == ["stream_open",
                                                 "stream_close"]


def test_prefill_phases_and_scheduler_events_in_vocabulary():
    """ISSUE 17: the unified prefill+decode scheduler speaks the
    closed vocabulary too — ``prefill_chunk`` spans each chunked-
    prefill slice of a prompt, ``stream_admitted`` fires on slot+page
    grant, ``prefill_complete`` when the last chunk lands. A
    vocabulary miss would make chunked prefill raise on the first
    admitted prompt."""
    assert "prefill_chunk" in trace_mod.PHASES
    sink = SpanCollector()
    ctx = trace_mod.start_trace(origin="decode", sink=sink)
    ctx.record("prefill_chunk", duration_s=0.001, stream="s1",
               chunk=8, fed=8)
    assert [s["phase"] for s in sink.spans] == ["prefill_chunk"]

    log = EventLog()
    validate_event(log.emit("stream_admitted", stream="s1", pages=4,
                            tenant="default"))
    validate_event(log.emit("prefill_complete", stream="s1",
                            prompt_tokens=9, chunks=2, tenant="default"))
    with pytest.raises(ValueError, match="missing required"):
        log.emit("prefill_complete", stream="s1",
                 tenant="default")  # counts required
    assert [e["type"] for e in log.events()] == ["stream_admitted",
                                                 "prefill_complete"]


def test_event_log_ring_and_jsonl_mirror(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path)
    log.emit("guard_skip", step=7)
    log.emit("exec_cache", bucket="b4_s16", hit=True)
    assert [e["type"] for e in log.events()] == ["guard_skip",
                                                "exec_cache"]
    assert [e["step"] for e in log.events("guard_skip")] == [7]
    with open(path, encoding="utf-8") as f:
        lines = [json.loads(ln) for ln in f]
    assert lines == log.events()
    for event in lines:
        validate_event(event)


def test_event_log_size_rotation(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, max_bytes=256, max_backups=2)
    for step in range(64):
        log.emit("guard_skip", step=step)
    assert os.path.exists(path)
    assert os.path.exists(path + ".1")
    assert not os.path.exists(path + ".3")  # backups bounded
    assert os.path.getsize(path) <= 256 + 128  # one line of slack
    # the ring ignores rotation entirely
    assert len(log.events("guard_skip")) == 64


def test_default_log_honors_env_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(events_mod.ENV_VAR, str(tmp_path))
    prev = events_mod.set_default_log(None)
    try:
        events_mod.emit("health_transition", old="READY", new="DEGRADED")
        expected = tmp_path / f"events-{os.getpid()}.jsonl"
        assert events_mod.default_log().path == str(expected)
        (line,) = [json.loads(ln) for ln in expected.read_text()
                   .splitlines()]
        assert line["type"] == "health_transition"
    finally:
        events_mod.set_default_log(prev)


# --- exposition parsing / label escaping / aggregation -----------------------


def test_label_value_escape_roundtrip():
    for value in ('plain', 'back\\slash', 'quo"te', 'new\nline',
                  'all\\"\nthree'):
        assert unescape_label_value(escape_label_value(value)) == value


def test_registry_render_parse_roundtrip_with_hostile_labels():
    registry = MetricsRegistry()
    counter = registry.counter("serving_requests_total", "by outcome")
    hostile = 'he said "no"\nand \\ left'
    counter.labels(outcome=hostile).inc(3)
    families = promparse.parse(registry.render())
    (sample,) = families["serving_requests_total"].samples
    assert sample.labels["outcome"] == hostile
    assert sample.value == 3
    assert promparse.check_exposition(registry.render()) == []


def test_conformance_catches_bad_expositions():
    untyped = "serving_mystery_total 3\n"
    assert any("without a # TYPE" in p
               for p in promparse.check_exposition(untyped))
    non_monotone = (
        "# TYPE serving_latency histogram\n"
        'serving_latency_bucket{le="0.1"} 5\n'
        'serving_latency_bucket{le="1"} 3\n'
        'serving_latency_bucket{le="+Inf"} 3\n'
        "serving_latency_count 3\n"
        "serving_latency_sum 1.0\n")
    assert any("not cumulative" in p
               for p in promparse.check_exposition(non_monotone))
    no_inf = (
        "# TYPE serving_latency histogram\n"
        'serving_latency_bucket{le="1"} 3\n'
        "serving_latency_count 3\n"
        "serving_latency_sum 1.0\n")
    assert any("+Inf" in p for p in promparse.check_exposition(no_inf))
    inf_mismatch = (
        "# TYPE serving_latency histogram\n"
        'serving_latency_bucket{le="+Inf"} 4\n'
        "serving_latency_count 3\n"
        "serving_latency_sum 1.0\n")
    assert any("_count" in p
               for p in promparse.check_exposition(inf_mismatch))


def test_merge_expositions_injects_replica_label():
    replica = ("# TYPE serving_bucket_dispatch_total counter\n"
               'serving_bucket_dispatch_total{bucket="b4_s16"} 2\n')
    router = ("# TYPE fleet_size gauge\nfleet_size 2\n")
    merged = merge_expositions({"r0": replica, "r1": replica},
                               extra_texts=(router,))
    assert promparse.check_exposition(merged) == []
    families = promparse.parse(merged)
    dispatch = families["serving_bucket_dispatch_total"]
    assert {s.labels["replica"] for s in dispatch.samples} == {"r0", "r1"}
    assert all(s.labels["bucket"] == "b4_s16" for s in dispatch.samples)
    # router series appended verbatim, unlabeled
    (size,) = families["fleet_size"].samples
    assert "replica" not in size.labels


def test_merge_expositions_rejects_kind_mismatch():
    a = "# TYPE serving_queue_depth gauge\nserving_queue_depth 1\n"
    b = "# TYPE serving_queue_depth counter\nserving_queue_depth 1\n"
    with pytest.raises(promparse.ParseError, match="kind mismatch"):
        merge_expositions({"r0": a, "r1": b})


def test_serving_batcher_registry_conforms():
    """The batcher's serving_* registry renders a clean exposition
    after real traffic (histograms populated, counters ticked)."""
    from perceiver_tpu.serving.batcher import MicroBatcher

    registry = MetricsRegistry()
    batcher = MicroBatcher(lambda batch: [{"ok": True} for _ in batch],
                           max_batch=4, max_delay_ms=1.0,
                           metrics=registry)
    try:
        futures = [batcher.submit({"i": i}) for i in range(6)]
        for fut in futures:
            fut.result(timeout=10)
    finally:
        batcher.close()
    assert promparse.check_exposition(registry.render()) == []


def test_decode_page_pool_gauges_conform_and_aggregate():
    """ISSUE 19 satellite: the decode arenas export occupancy gauges
    (``serving_page_pool_used_pages`` / ``_free_pages``, one sample per
    arena — ``target`` always, ``draft`` when speculation is on) that
    render a clean exposition and survive fleet aggregation with the
    replica label injected."""
    import numpy as np

    from perceiver_tpu.serving.decode import DecodeEngine, DecodeGeometry
    from perceiver_tpu.serving.speculative import SpeculativeConfig
    from perceiver_tpu.tasks import MaskedLanguageModelTask

    task = MaskedLanguageModelTask(
        vocab_size=110, max_seq_len=16, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    geometry = DecodeGeometry(max_streams=2, num_pages=9, page_size=4,
                              max_seq_len=16, max_chunk=4, spec_k=1)
    engine = DecodeEngine(task, geometry=geometry, auto_step=True,
                          speculative=SpeculativeConfig())
    try:
        h = engine.submit(np.array([5, 7, 9], np.int32),
                          max_new_tokens=3)
        assert h.result(120.0).finished == "complete"
        text = engine.metrics.render()
    finally:
        engine.close()
    assert promparse.check_exposition(text) == []
    families = promparse.parse(text)
    for name in ("serving_page_pool_used_pages",
                 "serving_page_pool_free_pages"):
        arenas = {s.labels["arena"] for s in families[name].samples}
        assert arenas == {"target", "draft"}, (name, arenas)
    # the stream drained, so both arenas read fully free
    used = {s.labels["arena"]: s.value
            for s in families["serving_page_pool_used_pages"].samples}
    assert used == {"target": 0.0, "draft": 0.0}
    free = {s.labels["arena"]: s.value
            for s in families["serving_page_pool_free_pages"].samples}
    assert free["target"] == float(geometry.allocatable_pages)
    assert free["draft"] == float(geometry.allocatable_pages)
    # and the per-replica exposition merges through the fleet
    # aggregator with the replica label injected on every arena sample
    merged = merge_expositions({"r0": text, "r1": text})
    assert promparse.check_exposition(merged) == []
    pool = promparse.parse(merged)["serving_page_pool_used_pages"]
    assert {s.labels["replica"] for s in pool.samples} == {"r0", "r1"}
    assert {s.labels["arena"] for s in pool.samples} == {"target",
                                                         "draft"}


def test_fleet_router_registry_conforms():
    from perceiver_tpu.fleet.router import Router

    router = Router(prober_interval_s=None)
    try:
        assert promparse.check_exposition(router.metrics.render()) == []
    finally:
        router.close()


def test_training_telemetry_registry_conforms(tmp_path):
    telemetry = Telemetry(str(tmp_path))
    telemetry.step(1, 2.5, steps_per_sec=4.0, samples_per_sec=128.0)
    telemetry.guard_skip(2)
    assert promparse.check_exposition(telemetry.registry.render()) == []


# --- HTTP endpoint -----------------------------------------------------------


def _get(url: str):
    req = urllib.request.Request(url)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read().decode("utf-8"), \
                resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:  # 4xx/5xx still carry a body
        return e.code, e.read().decode("utf-8"), \
            e.headers.get("Content-Type", "")


def test_obs_server_endpoints():
    registry = MetricsRegistry()
    registry.gauge("fleet_size", "replicas").set(2)
    buf = TraceBuffer()
    ctx = trace_mod.start_trace(sink=buf)
    ctx.record("submit", duration_s=0.001)
    healthy = {"flag": True}
    server = ObsServer(
        metrics_fn=registry.render,
        health_fn=lambda: {"ok": healthy["flag"]},
        trace_buffer=buf)
    try:
        status, body, ctype = _get(f"{server.url}/metrics")
        assert status == 200 and "version=0.0.4" in ctype
        assert promparse.check_exposition(body) == []

        status, body, _ = _get(f"{server.url}/healthz")
        assert status == 200 and json.loads(body)["ok"] is True
        healthy["flag"] = False
        status, _, _ = _get(f"{server.url}/healthz")
        assert status == 503

        status, body, _ = _get(f"{server.url}/traces")
        assert status == 200
        assert json.loads(body)["traces"] == [ctx.trace_id]

        status, body, _ = _get(f"{server.url}/traces/{ctx.trace_id}")
        assert status == 200
        payload = json.loads(body)
        assert payload["trace_id"] == ctx.trace_id
        assert [s["phase"] for s in payload["spans"]] == ["submit"]

        status, _, _ = _get(f"{server.url}/traces/nonexistent")
        assert status == 404
        status, _, _ = _get(f"{server.url}/nope")
        assert status == 404
        # no profile_dir configured -> 501, never a crash
        status, body, _ = _get(f"{server.url}/profile?seconds=1")
        assert status == 501 and "profile_dir" in body
    finally:
        server.close()


# --- training telemetry ------------------------------------------------------


def test_telemetry_jsonl_and_counters(tmp_path):
    telemetry = Telemetry(str(tmp_path))
    telemetry.step(10, 1.25, steps_delta=5, steps_per_sec=50.0,
                   samples_per_sec=1600.0, mfu=0.31)
    telemetry.step(20, 1.10, steps_delta=10)
    telemetry.guard_skip(21)
    telemetry.guard_rewind(22)
    telemetry.checkpoint_seal(str(tmp_path / "ckpt-20"))
    telemetry.preempt_checkpoint(23)

    with open(tmp_path / "telemetry.jsonl", encoding="utf-8") as f:
        lines = [json.loads(ln) for ln in f]
    for event in lines:
        validate_event(event)
    steps = [e for e in lines if e["type"] == "train_step"]
    assert [e["step"] for e in steps] == [10, 20]
    assert steps[0]["mfu"] == pytest.approx(0.31)  # extras kept

    registry = telemetry.registry
    assert registry.get("training_steps_total").value == 15
    assert registry.get("training_loss").value == pytest.approx(1.10)
    assert registry.get("training_guard_skips_total").value == 1
    assert registry.get("training_guard_rewinds_total").value == 1
    assert registry.get("training_checkpoint_seals_total").value == 1
    assert registry.get("training_preempt_checkpoints_total").value == 1


def test_signal_profiler_install_uninstall(tmp_path):
    import signal

    prev_handler = signal.getsignal(signal.SIGUSR1)
    uninstall = install_signal_profiler(str(tmp_path))
    assert callable(uninstall)
    assert signal.getsignal(signal.SIGUSR1) is not prev_handler
    uninstall()
    assert signal.getsignal(signal.SIGUSR1) is prev_handler


def test_signal_profiler_off_main_thread_degrades(tmp_path):
    result = {}

    def worker():
        result["value"] = install_signal_profiler(str(tmp_path))

    t = threading.Thread(target=worker)
    t.start()
    t.join(10)
    assert result["value"] is None  # manual profiling, no crash


# --- overhead budget ---------------------------------------------------------


def test_tracing_overhead_within_pinned_bounds():
    """The hot-path budget the plane promises: a span record is a dict
    build + list append (<100us, ~2us in practice); the disabled
    ``start_trace`` is one global read (<10us, ~0.1us)."""
    import time

    ctx = trace_mod.start_trace(sink=SpanCollector())
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        ctx.record("dispatch", duration_s=0.0)
    per_span_us = (time.perf_counter() - t0) / n * 1e6
    try:
        trace_mod.set_enabled(False)
        t0 = time.perf_counter()
        for _ in range(n):
            trace_mod.start_trace()
        disabled_us = (time.perf_counter() - t0) / n * 1e6
    finally:
        trace_mod.set_enabled(True)
    assert per_span_us < 100.0, per_span_us
    assert disabled_us < 10.0, disabled_us


# --- integration gates -------------------------------------------------------


def test_obs_check_fast_gate():
    """``scripts/obs_check.py --fast`` as a literal subprocess gate:
    a real 2-replica fleet under traced traffic proves the e2e trace,
    the aggregated exposition, the event log, the zero-compile budget,
    and the overhead bounds — all in one fresh process."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_check.py"),
         "--fast"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, f"\n{proc.stdout}\n{proc.stderr}"

    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    by_metric = {ln["metric"]: ln for ln in lines}
    for line in lines:
        assert {"metric", "value", "unit", "vs_baseline",
                "detail"} <= set(line)
    assert by_metric["obs_check"]["value"] == 1.0
    checks = [ln for ln in lines if ln["metric"] != "obs_check"]
    assert len(checks) == 5
    assert all(ln["value"] == 1.0 for ln in checks)
    trace_detail = by_metric["obs_trace_complete"]["detail"]
    assert trace_detail["processes"] >= 2
    deltas = by_metric["obs_zero_compiles"]["detail"][
        "post_warmup_compile_deltas"]
    assert deltas and all(d == 0 for d in deltas.values())


def test_fleet_kill_yields_one_trace_with_retry(tmp_path, monkeypatch):
    """ISSUE acceptance: SIGKILL a replica mid-dispatch and prove ONE
    trace — fetched from the live ``/traces/<id>`` socket — carries
    the failed ``rpc_hop``, the ``retry``, the re-``route``, and the
    sibling's server-side spans, across at least two processes."""
    import numpy as np

    from perceiver_tpu.fleet import Fleet
    from perceiver_tpu.serving.errors import ServingError
    from perceiver_tpu.serving.graphs import build_serve_graph
    from perceiver_tpu.tasks import MaskedLanguageModelTask
    from perceiver_tpu.training.checkpoint import ParamsVersionStore

    task_kwargs = dict(
        vocab_size=110, max_seq_len=32, num_latents=4,
        num_latent_channels=8, num_encoder_layers=1,
        num_encoder_self_attention_layers_per_block=1,
        num_encoder_cross_attention_heads=1,
        num_encoder_self_attention_heads=1,
        num_decoder_cross_attention_heads=1, loss_impl="dense")
    graph = build_serve_graph(MaskedLanguageModelTask(**task_kwargs))
    store = ParamsVersionStore(str(tmp_path / "store"))
    store.publish("v1", graph.init_params(0), set_current=True)
    spec = {"task_class": "MaskedLanguageModelTask",
            "task_kwargs": task_kwargs,
            "batch_buckets": [4], "seq_buckets": [16],
            "store_dir": store.directory, "version": "v1", "seed": 0}
    monkeypatch.setenv("PERCEIVER_EXEC_CACHE",
                       str(tmp_path / "exec_cache"))

    buf = TraceBuffer(max_traces=512)
    prev_buf = trace_mod.set_default_buffer(buf)
    # r0 SIGKILLs itself mid-dispatch on its 3rd request; r1 is the
    # surviving sibling the router must fail over to
    fleet = Fleet(
        spec, str(tmp_path / "fleet"), replicas=2, max_restarts=3,
        dispatch_timeout_s=10.0,
        per_replica_env={"r0": {
            "PERCEIVER_FAULTS": "replica.crash@at=2"}})
    try:
        obs = fleet.start_obs()
        rng = np.random.default_rng(0)
        retried_id = None
        for _ in range(40):
            arrays = {"input_ids": rng.integers(
                          3, 110, (2, 16)).astype(np.int32),
                      "pad_mask": np.zeros((2, 16), bool)}
            try:
                reply = fleet.submit(arrays)
            except ServingError:
                continue  # typed refusal mid-crash — keep driving
            tid = reply.get("trace_id")
            spans = buf.get(tid) or []
            if any(s["phase"] == "retry" for s in spans):
                retried_id = tid
                break
        assert retried_id is not None, "no request ever hit the crash"

        status, body, _ = _get(f"{obs.url}/traces/{retried_id}")
        assert status == 200, (status, body)
        payload = json.loads(body)
        assert payload["trace_id"] == retried_id
        spans = payload["spans"]
        assert all(s["trace_id"] == retried_id for s in spans)

        by_phase = {}
        for s in spans:
            by_phase.setdefault(s["phase"], []).append(s)
        # the failed hop, the backoff, and the re-route are all there
        failed = [s for s in by_phase["rpc_hop"]
                  if (s.get("attrs") or {}).get("ok") is False]
        ok = [s for s in by_phase["rpc_hop"]
              if (s.get("attrs") or {}).get("ok") is True]
        assert failed and ok, by_phase["rpc_hop"]
        assert "retry" in by_phase
        assert len(by_phase["route"]) >= 2  # picked, failed, re-picked
        # the sibling's server-side spans were absorbed into the SAME
        # trace, tagged with the survivor's id, from another process
        survivor = (ok[0].get("attrs") or {})["replica"]
        assert survivor != (failed[0].get("attrs") or {})["replica"]
        for phase in ("queue_wait", "pad_or_pack", "dispatch", "device"):
            assert phase in by_phase, sorted(by_phase)
            tags = [(s.get("attrs") or {}).get("replica")
                    for s in by_phase[phase]]
            assert survivor in tags, (phase, tags)
        assert len({s["pid"] for s in spans}) >= 2
    finally:
        fleet.close()
        trace_mod.set_default_buffer(prev_buf)
