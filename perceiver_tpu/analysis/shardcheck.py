"""Shardcheck: sharding-aware passes over lowered SPMD graphs.

Three passes, one failure philosophy (docs/ANALYSIS.md): the
properties SPMD scale-out lives or dies on are statically visible in
the lowered/compiled module, so they are gated there — before a chip
ever runs the program.

``collective_budget``
    GSPMD inserts every collective at compile time, so the pass walks
    the *optimized* HLO (``LoweredStep.compiled_text``) for
    all-reduce / all-gather / reduce-scatter / collective-permute /
    all-to-all, attributes each op's bytes to the mesh-axis subset its
    replica groups span (``hlo.attribute_axis``), and gates the
    per-axis byte totals against the checked-in manifest
    (``shard_budgets.json``). Axis traffic above budget — or on an
    axis with no budget at all — fails the merge: on a real slice the
    data axis is DCN/ICI once per step while the model axis pays per
    layer, so "some new collective appeared" is exactly the class of
    regression that must not land silently.

``replication_check``
    A tensor the sharding rules declared sharded must not materialize
    fully replicated: the pass scans the @main boundary (args +
    results) and mid-graph ``@Sharding`` custom calls of the StableHLO
    for tensors at or above a size floor whose annotation replicates
    them, modulo a per-target ``ReplicationAllow`` list (the audit
    trail for read-only tables that are replicated by design). This is
    the static form of "the step silently all-gathers the full
    parameter pytree" — the pjit scaling postmortem classic.

``per_shard_hbm_budget``
    The global hbm_budget divided by the mesh: cost-analysis bytes ÷
    device count, pinned per target in the same manifest. Pins the
    figure that actually has to fit one device's HBM, so halving the
    mesh or un-sharding a large buffer cannot hide inside the global
    number.

Re-baseline protocol mirrors hbm_budget: ``scripts/check.py
--rebaseline-shard`` rewrites the manifest from fresh measurements
(``--pin-missing-shard`` budgets only new targets); the manifest diff
is the audit trail of every accepted regression.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from perceiver_tpu.analysis import hlo
from perceiver_tpu.analysis.report import ReplicationAllow, Violation

_SHARD_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "shard_budgets.json")
# collective placement moves with GSPMD heuristics across jax versions
# more than cost-analysis bytes do, so the headroom is looser than
# hbm_budget's 1.05 — still tight enough that a new per-layer
# all-gather (≥2× on its axis) trips
_SHARD_HEADROOM = 1.10
# tensors under 1 MiB may replicate freely (norm scales, biases,
# descriptors); above it, replication must be declared
DEFAULT_FLOOR_BYTES = 1 << 20


def load_shard_budgets(path: Optional[str] = None) -> Dict[str, dict]:
    """Target-name → manifest entry (``{mesh, collectives, per_shard,
    pinned}``). Empty when absent — every mesh target then fails with
    a missing-budget violation, so a deleted manifest cannot read as a
    clean tree."""
    try:
        with open(path or _SHARD_MANIFEST) as f:
            return json.load(f)["targets"]
    except (OSError, KeyError, ValueError):
        return {}


def write_shard_budgets(measured: Dict[str, dict],
                        path: Optional[str] = None,
                        headroom: float = _SHARD_HEADROOM,
                        note: str = "",
                        keep: Optional[Dict[str, dict]] = None) -> dict:
    """Re-baseline the shard manifest. ``measured`` maps target name →
    ``{"mesh": descriptor, "collectives": {axis: bytes},
    "per_shard": bytes, "ops": {...}}`` (``ops`` is informational and
    copied through). ``keep`` copies already-pinned entries verbatim —
    the ``--pin-missing-shard`` path."""
    def entry(m: dict) -> dict:
        return {
            "mesh": m["mesh"],
            "collectives": {
                axis: {"pinned_bytes": int(b),
                       "budget_bytes": int(b * headroom)}
                for axis, b in sorted(m["collectives"].items())},
            "per_shard": {
                "pinned_bytes": int(m["per_shard"]),
                "budget_bytes": int(m["per_shard"] * headroom)},
            "ops": m.get("ops", {}),
            "pinned": note,
        }

    manifest = {
        "_comment": (
            "shardcheck manifest — per-mesh-axis collective bytes "
            "(optimized HLO, CPU SPMD partitioning) and per-shard "
            "cost-analysis bytes per sharded canonical target. "
            f"budget_bytes = pinned_bytes x {headroom}. Re-baseline "
            "via scripts/check.py --rebaseline-shard after an "
            "intentional change; never edit budgets by hand to make "
            "a regression pass."),
        "targets": dict(sorted({
            **(keep or {}),
            **{name: entry(m) for name, m in measured.items()},
        }.items())),
    }
    with open(path or _SHARD_MANIFEST, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    return manifest


# --- collective inventory / budget -------------------------------------------


def collective_inventory(compiled_text: str, mesh) -> dict:
    """Per-axis collective totals from optimized HLO:
    ``{"collectives": {axis: bytes}, "ops": {axis: {op: count}}}``.
    ``mesh`` is a ``targets.MeshSpec``. Degenerate ops whose replica
    groups are all singletons move no bytes and are skipped."""
    shape, names = list(mesh.shape), list(mesh.axis_names)
    by_axis: Dict[str, int] = {}
    ops: Dict[str, Dict[str, int]] = {}
    for col in hlo.iter_collectives(compiled_text):
        if all(len(g) <= 1 for g in col["groups"]):
            continue
        axis = hlo.attribute_axis(col["groups"], shape, names)
        by_axis[axis] = by_axis.get(axis, 0) + col["bytes"]
        ops.setdefault(axis, {})
        ops[axis][col["op"]] = ops[axis].get(col["op"], 0) + 1
    return {"collectives": by_axis, "ops": ops}


def collective_budget(compiled_text: Optional[str], mesh, *, where: str,
                      budgets: Dict[str, dict],
                      ) -> Tuple[List[Violation], dict]:
    """Per-axis collective bytes must stay within the target's pinned
    budgets; traffic on an unbudgeted axis is itself a violation (a
    brand-new collective class must be pinned, not waved through).
    Returns ``(violations, inventory)``."""
    if compiled_text is None:
        return [Violation(
            check="collective_budget", where=where,
            message="no compiled HLO available for this mesh target — "
                    "lower_target(want_compiled=True) is required; "
                    "collectives only exist post-SPMD-partitioning")], {}
    inventory = collective_inventory(compiled_text, mesh)
    entry = budgets.get(where)
    if entry is None:
        return [Violation(
            check="collective_budget", where=where,
            message="no collective budget pinned for this target in "
                    "shard_budgets.json — run scripts/check.py "
                    "--rebaseline-shard and commit the manifest")], inventory
    violations = []
    pinned_axes = entry.get("collectives", {})
    if entry.get("mesh") != mesh.descriptor:
        violations.append(Violation(
            check="collective_budget", where=where,
            message=f"manifest pinned mesh {entry.get('mesh')!r} but the "
                    f"target now lowers over {mesh.descriptor!r} — "
                    "re-baseline so budgets match the topology"))
    for axis, measured in sorted(inventory["collectives"].items()):
        pin = pinned_axes.get(axis)
        if pin is None:
            violations.append(Violation(
                check="collective_budget", where=where,
                message=f"{measured / 1e6:.2f} MB of collective traffic "
                        f"on unbudgeted mesh axis {axis!r} "
                        f"({inventory['ops'][axis]}) — a new collective "
                        "class appeared; pin it via scripts/check.py "
                        "--rebaseline-shard if intentional"))
            continue
        budget = float(pin["budget_bytes"])
        if measured > budget:
            pinned = float(pin.get("pinned_bytes", budget))
            violations.append(Violation(
                check="collective_budget", where=where,
                message=f"{measured / 1e6:.2f} MB moved on mesh axis "
                        f"{axis!r} exceeds the pinned budget "
                        f"{budget / 1e6:.2f} MB "
                        f"({100 * (measured / pinned - 1):+.1f}% vs "
                        "baseline) — collective traffic regressed "
                        f"({inventory['ops'][axis]}); fix the sharding "
                        "or re-baseline via --rebaseline-shard with "
                        "justification"))
    return violations, inventory


# --- replication / resharding detector ---------------------------------------

# mid-graph sharding constraints print as
#   %2 = stablehlo.custom_call @Sharding(%1) {mhlo.sharding = "..."}
#       : (tensor<...>) -> tensor<512x64xf32>
_MIDGRAPH_SHARDING = re.compile(
    r'custom_call @Sharding\(.*?mhlo\.sharding = "([^"]*)"'
    r'.*?->\s*tensor<([^>]+)>')


def replication_check(text: str, *, where: str,
                      floor_bytes: int = DEFAULT_FLOOR_BYTES,
                      allowlist: Sequence[ReplicationAllow] = (),
                      ) -> List[Violation]:
    """No tensor ≥ ``floor_bytes`` may be fully replicated at the
    @main boundary or resharded to replicated mid-graph, outside the
    allowlist. Runs on the StableHLO of a pjit-lowered module (where
    every boundary tensor carries ``mhlo.sharding``)."""
    suspects: List[Tuple[str, str, str]] = []  # (site, type, sharding)
    for a in hlo.main_args(text):
        suspects.append(("arg", a["type"], a["sharding"]))
    for r in hlo.main_results(text):
        suspects.append(("result", r["type"], r["sharding"]))
    for m in _MIDGRAPH_SHARDING.finditer(text):
        suspects.append(("mid-graph @Sharding", m.group(2), m.group(1)))
    budgets = {id(a): a.max_count for a in allowlist}
    violations = []
    for site, ty, sharding in suspects:
        if hlo.sharding_factor(sharding) != 1:
            continue
        size = hlo.tensor_bytes(ty)
        if size < floor_bytes:
            continue
        hit = next((a for a in allowlist
                    if a.type == ty and budgets[id(a)] > 0), None)
        if hit is not None:
            budgets[id(hit)] -= 1
            continue
        violations.append(Violation(
            check="replication_check", where=where,
            message=f"{site} tensor<{ty}> ({size / 1e6:.2f} MB) is "
                    "fully replicated — every device holds a whole "
                    "copy despite the declared shardings; shard it "
                    "(parallel/sharding.py) or record a reasoned "
                    "ReplicationAllow on the target"))
    return violations


# --- per-shard HBM budget ----------------------------------------------------


def per_shard_hbm_budget(bytes_accessed: Optional[float], mesh, *,
                         where: str, budgets: Dict[str, dict],
                         ) -> List[Violation]:
    """Cost-analysis bytes ÷ mesh devices must stay within the pinned
    per-shard budget — the figure that has to fit ONE device's HBM."""
    entry = budgets.get(where)
    if entry is None or "per_shard" not in entry:
        return [Violation(
            check="per_shard_hbm_budget", where=where,
            message="no per-shard byte budget pinned for this target "
                    "in shard_budgets.json — run scripts/check.py "
                    "--rebaseline-shard and commit the manifest")]
    if bytes_accessed is None:
        return [Violation(
            check="per_shard_hbm_budget", where=where,
            message="lowering exposed no cost analysis, so the "
                    "per-shard budget cannot be checked — run on a "
                    "backend with lowering-time cost analysis (CPU)")]
    per_shard = bytes_accessed / mesh.n_devices
    pin = entry["per_shard"]
    budget = float(pin["budget_bytes"])
    if per_shard > budget:
        pinned = float(pin.get("pinned_bytes", budget))
        return [Violation(
            check="per_shard_hbm_budget", where=where,
            message=f"per-shard bytes {per_shard / 1e9:.2f} GB "
                    f"(global ÷ {mesh.n_devices}) exceeds the pinned "
                    f"budget {budget / 1e9:.2f} GB "
                    f"({100 * (per_shard / pinned - 1):+.1f}% vs "
                    "baseline) — a buffer stopped sharding or the step "
                    "regressed; fix it or re-baseline via "
                    "--rebaseline-shard with justification")]
    return []


def run_shard_passes(lowered, *, budgets: Dict[str, dict],
                     floor_bytes: int = DEFAULT_FLOOR_BYTES,
                     ) -> Tuple[List[Violation], dict]:
    """All three shardcheck passes over one mesh ``LoweredStep``.
    Returns ``(violations, inventory)`` — the inventory feeds the
    manifest pin paths in scripts/check.py."""
    target = lowered.target
    vs, inventory = collective_budget(
        lowered.compiled_text, target.mesh, where=target.name,
        budgets=budgets)
    vs += replication_check(
        lowered.text, where=target.name, floor_bytes=floor_bytes,
        allowlist=target.replication_allow)
    vs += per_shard_hbm_budget(
        lowered.bytes_accessed, target.mesh, where=target.name,
        budgets=budgets)
    return vs, inventory
