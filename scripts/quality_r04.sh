#!/bin/bash
# Consolidated round-4 quality evidence → QUALITY_r04.json: the
# completed 14k-step MLM schedule (curve + final validate), pointers
# to the 3-seed coherence table and the BoW certificate, and the
# graph-audit perf findings. Rerunnable; run once more right before
# round end to capture the latest arms.
set -u
cd "$(dirname "$0")/.."

FINAL_VAL=""
if [[ -f logs/mlm_final_validate_r04.log ]]; then
  FINAL_VAL=$(grep -oE "val_loss[:=] ?[0-9.]+" \
              logs/mlm_final_validate_r04.log \
              | tail -1 | grep -oE "[0-9.]+$")
fi

python - "$FINAL_VAL" <<'EOF' > QUALITY_r04.json.tmp
import json, subprocess, sys

final_val = sys.argv[1] or None

def summary(*exps):
    out = subprocess.run(
        [sys.executable, "scripts/quality_summary.py", *exps],
        capture_output=True, text=True)
    lines = out.stdout.splitlines()
    start = next((i for i, l in enumerate(lines) if l.startswith("{")),
                 None)
    if out.returncode != 0 or start is None:
        sys.stderr.write(out.stderr)
        sys.exit(f"quality_summary failed (rc={out.returncode}) for "
                 f"{exps}")
    return json.loads("\n".join(lines[start:]))

doc = {
    "round": 4,
    "mlm_pretraining": summary("mlm_quality", "mlm_cpu_quality"),
    "mlm_final_validate": {
        "step": 14000,
        "val_loss": float(final_val) if final_val else None,
        "platform": "cpu",
        "note": ("completed 14k-step OneCycle schedule (VERDICT r3 "
                 "next #6); reproduce with scripts/mlm.py validate "
                 "--ckpt_path=<furthest mlm_quality ckpt>"),
    },
    "coherence_transfer": ("see QUALITY_r04_coherence.json (3-seed "
                           "full-label arms on .cache_coh4: val 806, "
                           "contamination-free unseen-pool val docs)"),
    "bow_control": "see QUALITY_r04_bow_control.json (at-chance)",
    "perf_graph_audit": ("see logs/hlo_audit_r04_b512_c64.json — "
                         "bf16_flop_fraction 1.0 after the bf16-"
                         "cotangent fix; K-ceiling 0.657 (C=64) / "
                         "0.919 (C=128)"),
    "egress_retry": ("aclImdb + published-ckpt hosts retried this "
                     "session: DNS failure (zero egress still)"),
}
json.dump(doc, sys.stdout, indent=1)
EOF
rc=$?
if (( rc == 0 )); then
  echo "" >> QUALITY_r04.json.tmp
  mv QUALITY_r04.json.tmp QUALITY_r04.json
  python -c "import json; d=json.load(open('QUALITY_r04.json')); \
print('QUALITY_r04.json ok:', list(d))"
else
  rm -f QUALITY_r04.json.tmp
  exit "$rc"
fi
