#!/usr/bin/env python
"""Build a pool of harvest-source paragraphs PROVABLY unseen by the
MLM pretraining run, for contamination-free coherence-val construction.

``harvest_text.py`` balance-downsamples the majority style class, so a
large slice of the cleaned/deduplicated paragraph pool was never
written into ``.cache/aclImdb`` at all — never tokenized, never
pretrained on. This script re-walks the same sources with the same
cleaning, then keeps ONLY paragraphs whose exact text is absent from
every file under ``--seen`` (sha1 set over .cache/aclImdb/**): a
direct, reproducibility-independent disjointness proof. The survivors
are labeled with the harvest's style regex and written in the
``aclImdb/test/{pos,neg}`` layout so ``make_coherence_corpus.py
--extra-test-src`` can fold them into the coherence VAL split.

Why this matters (round-4 review finding): enlarging the coherence val
split by moving .cache TRAIN docs into it would hand the transfer
arm's encoder val documents it saw during MLM pretraining — inflating
the transfer-vs-scratch margin the whole experiment exists to measure.
This pool grows the val split only with text NO arm has ever seen.
"""

import argparse
import glob
import hashlib
import importlib.util
import os
import shutil
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_spec = importlib.util.spec_from_file_location(
    "harvest_text", os.path.join(_HERE, "harvest_text.py"))
harvest = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(harvest)


def _seen_hashes(seen_root: str) -> set:
    seen = set()
    for path in glob.glob(os.path.join(seen_root, "aclImdb", "*", "*",
                                       "*.txt")):
        with open(path, encoding="utf-8") as f:
            seen.add(hashlib.sha1(f.read().encode()).digest()[:8])
    return seen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seen", default=".cache",
                    help="harvest root whose aclImdb/** contents the "
                         "MLM pretrained on — nothing matching goes "
                         "into the pool")
    ap.add_argument("--out", default=".cache_unseen")
    ap.add_argument("--max-docs", type=int, default=60_000)
    args = ap.parse_args()

    seen = _seen_hashes(args.seen)
    if not seen:
        sys.exit(f"no harvested docs under {args.seen}/aclImdb — "
                 "run harvest_text.py first")
    print(f"seen-paragraph hashes: {len(seen)}", flush=True)

    site_dirs = [p for p in sys.path if p.endswith("site-packages")]
    doc_roots = site_dirs + ["/usr/share/doc"]

    pool, pool_seen = [], set()

    def add(text):
        for para in harvest._clean_paragraphs(text):
            h = hashlib.sha1(para.encode()).digest()[:8]
            if h in seen or h in pool_seen:
                continue
            pool_seen.add(h)
            pool.append(para)

    for path in harvest._iter_doc_files(doc_roots):
        try:
            with open(path, encoding="utf-8", errors="ignore") as f:
                add(f.read())
        except OSError:
            continue
        if len(pool) >= args.max_docs:
            break
    if len(pool) < args.max_docs:
        for doc in harvest._iter_docstrings(site_dirs):
            add(doc)
            if len(pool) >= args.max_docs:
                break

    out_root = os.path.join(args.out, "aclImdb", "test")
    shutil.rmtree(os.path.join(args.out, "aclImdb"), ignore_errors=True)
    counts = {0: 0, 1: 0}
    for label in ("neg", "pos"):
        os.makedirs(os.path.join(out_root, label), exist_ok=True)
    for i, doc in enumerate(pool):
        y = int(bool(harvest._API_WORDS.search(doc)))
        counts[y] += 1
        with open(os.path.join(out_root, ("neg", "pos")[y],
                               f"u{i}_{5 + y * 5}.txt"), "w",
                  encoding="utf-8") as f:
            f.write(doc)
    print(f"unseen pool: {len(pool)} docs "
          f"(prose {counts[0]} / api {counts[1]}) -> {out_root}",
          flush=True)


if __name__ == "__main__":
    main()
