"""Request tracing: dependency-free trace contexts and span buffers.

One request, one ``trace_id``, many *spans* — each span is a typed
phase (``queue_wait``, ``batch_form``, ``pad_or_pack``, ``dispatch``,
``device``, ``rpc_hop``, ``retry``, ``route``) with a monotonic-clock
start/end measured in the process that did the work.  The context is
created where the request enters the system (``api.submit`` /
``Fleet.submit``), rides the fleet RPC envelope as a tiny wire dict
(``{"trace_id", "parent_id"}``), and the replica ships its locally
collected spans back in the dispatch reply so the router can absorb
them into one trace.  A retried request therefore yields a SINGLE
trace with the failed hop, the ``retry`` span, and the sibling's
server-side spans all visible.

Everything here is host-side Python: no jax imports, no device work,
so tracing can never change an XLA cache key or add a compile.  When
tracing is disabled (``set_enabled(False)``), ``start_trace`` returns
``None`` and the hot-path cost of an instrumented call site collapses
to one thread-local attribute read.

Clock caveat: span ``start``/``end`` are ``time.monotonic`` values and
are only comparable *within* one process.  Cross-process ordering uses
the spans' ``wall`` field (coarse ``time.time``), durations are always
trustworthy.

This is *request* tracing; for XLA profiler traces (the other kind of
"trace") see ``scripts/trace_analysis.py`` and the ``/profile``
endpoint in :mod:`perceiver_tpu.obs.server`.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "PHASES",
    "TraceContext",
    "TraceBuffer",
    "SpanCollector",
    "start_trace",
    "from_wire",
    "attach",
    "attached",
    "region",
    "enabled",
    "set_enabled",
    "default_buffer",
    "set_default_buffer",
]

#: The typed phase vocabulary.  ``record``/``region`` reject anything
#: else so dashboards and tests can rely on a closed set.
PHASES = (
    "submit",       # client-side: request accepted into the system
    "queue_wait",   # batcher: enqueue -> popped into a batch
    "batch_form",   # batcher: popped -> batch handed to the runner
    "pad_or_pack",  # engine: host-side bucket padding / packing
    "dispatch",     # engine: executable launch (async, host cost only)
    "device",       # api: materialize (the deliberate device sync)
    "route",        # router: replica selection
    "rpc_hop",      # router: one RPC attempt against one replica
    "retry",        # router: backoff + re-pick after a failed hop
    "decode_step",  # decode engine: one stepped-executable iteration
    "prefill_chunk",  # decode engine: one chunked-prefill slice of a prompt
    "token_emit",   # decode engine: one generated token handed out
    "prefix_lookup",  # decode engine: prefix-cache probe at admission
    "draft",        # decode engine: draft-model proposal calls for one row
    "verify",       # decode engine: target verification of drafted tokens
)

_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool) -> None:
    """Process-wide tracing switch (used by the overhead gate tests)."""
    global _enabled
    _enabled = bool(flag)


def _new_id() -> str:
    return os.urandom(8).hex()


class SpanCollector:
    """A plain list sink for spans (replica side, per request).

    Replicas don't keep traces — they collect the spans a request
    produced locally and return them in the dispatch reply.
    """

    def __init__(self) -> None:
        self.spans: List[dict] = []

    def add(self, trace_id: str, span: dict) -> None:
        self.spans.append(span)


class TraceBuffer:
    """Bounded in-memory ring of traces (LRU-evicting, thread-safe)."""

    # spans arrive from every serving thread; the LRU OrderedDict and
    # the overflow counter move together under one lock
    _GUARDED = {"_traces": "_lock", "dropped_spans": "_lock"}

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 128) -> None:
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        self.dropped_spans = 0

    def add(self, trace_id: str, span: dict) -> None:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = []
                self._traces[trace_id] = spans
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            else:
                self._traces.move_to_end(trace_id)
            if len(spans) < self.max_spans_per_trace:
                spans.append(span)
            else:
                self.dropped_spans += 1

    def absorb(self, trace_id: str, spans: Iterable[dict],
               **extra_attrs) -> None:
        """Merge remotely collected spans into a trace, optionally
        tagging each with extra attrs (e.g. ``replica="r0"``)."""
        for span in spans:
            if extra_attrs:
                span = dict(span)
                attrs = dict(span.get("attrs") or {})
                attrs.update(extra_attrs)
                span["attrs"] = attrs
            self.add(trace_id, span)

    def get(self, trace_id: str) -> Optional[List[dict]]:
        with self._lock:
            spans = self._traces.get(trace_id)
            return list(spans) if spans is not None else None

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)


_default_buffer = TraceBuffer()


def default_buffer() -> TraceBuffer:
    return _default_buffer


def set_default_buffer(buffer: TraceBuffer) -> TraceBuffer:
    global _default_buffer
    prev = _default_buffer
    _default_buffer = buffer
    return prev


class TraceContext:
    """One request's trace handle.

    Spans are recorded *retrospectively*: the caller measures with
    whatever clocks it already has (``enqueued_at``, ``taken_at``) and
    calls :meth:`record` with explicit bounds, or uses the
    :func:`region` context manager for the simple wrap case.
    """

    __slots__ = ("trace_id", "parent_id", "origin", "_sink")

    def __init__(self, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 sink=None, origin: str = "") -> None:
        self.trace_id = trace_id or _new_id()
        self.parent_id = parent_id
        self.origin = origin
        self._sink = sink if sink is not None else _default_buffer

    def record(self, phase: str, *, start: Optional[float] = None,
               end: Optional[float] = None,
               duration_s: Optional[float] = None, **attrs) -> dict:
        if phase not in PHASES:
            raise ValueError(
                f"unknown trace phase {phase!r}; expected one of {PHASES}")
        if end is None:
            end = time.monotonic()
        if start is None:
            start = end - duration_s if duration_s is not None else end
        span = {
            "trace_id": self.trace_id,
            "phase": phase,
            "start": start,
            "end": end,
            "duration_s": round(end - start, 9),
            "wall": time.time(),
            "pid": os.getpid(),
        }
        if self.origin:
            span["origin"] = self.origin
        if attrs:
            span["attrs"] = attrs
        self._sink.add(self.trace_id, span)
        return span

    def wire(self) -> Dict[str, str]:
        """The cross-process envelope: small, picklable, stable."""
        out = {"trace_id": self.trace_id}
        if self.parent_id:
            out["parent_id"] = self.parent_id
        return out

    def absorb(self, spans: Iterable[dict], **extra_attrs) -> None:
        """Merge spans collected in another process into this trace
        (re-keyed to this ``trace_id``, optionally tagged — the router
        tags replica-side spans with the replica id)."""
        for span in spans:
            span = dict(span)
            span["trace_id"] = self.trace_id
            if extra_attrs:
                attrs = dict(span.get("attrs") or {})
                attrs.update(extra_attrs)
                span["attrs"] = attrs
            self._sink.add(self.trace_id, span)


def start_trace(origin: str = "",
                sink=None) -> Optional[TraceContext]:
    """Create a trace for a new request, or ``None`` when disabled.

    Call sites hold the possibly-``None`` context and guard with
    ``if ctx is not None`` — the disabled path does no allocation.
    """
    if not _enabled:
        return None
    return TraceContext(sink=sink, origin=origin)


def from_wire(wire: Optional[dict], sink=None,
              origin: str = "") -> Optional[TraceContext]:
    """Rehydrate a context from the RPC envelope dict (replica side)."""
    if not _enabled or not wire or "trace_id" not in wire:
        return None
    return TraceContext(trace_id=str(wire["trace_id"]),
                        parent_id=wire.get("parent_id"),
                        sink=sink, origin=origin)


# --- thread-local attachment ------------------------------------------------
# The engine runs one *batch* containing many requests; spans recorded
# inside the batcher's runner call must land in every member trace.
# ``attach`` binds the member contexts to the current thread, ``region``
# records one measured span into each.  Unattached regions are no-ops.

_tls = threading.local()


def attached() -> Tuple[TraceContext, ...]:
    return getattr(_tls, "ctxs", ())


@contextlib.contextmanager
def attach(ctxs: Sequence[Optional[TraceContext]]):
    prev = getattr(_tls, "ctxs", ())
    _tls.ctxs = tuple(c for c in ctxs if c is not None)
    try:
        yield
    finally:
        _tls.ctxs = prev


@contextlib.contextmanager
def region(phase: str, **attrs):
    """Record ``phase`` over the wrapped block into every attached
    trace.  Cost when nothing is attached: one getattr + tuple check."""
    ctxs = getattr(_tls, "ctxs", ())
    if not ctxs:
        yield
        return
    start = time.monotonic()
    try:
        yield
    finally:
        end = time.monotonic()
        for c in ctxs:
            c.record(phase, start=start, end=end, **attrs)
