"""Test environment: force an 8-device virtual CPU backend.

Runs before test collection imports anything heavy (SURVEY.md §4 test
plan item (c)): distributed tests exercise real pjit/Mesh code paths on
8 fake CPU devices, the idiomatic JAX substitute for a pod slice in CI.

The container's sitecustomize registers the ``axon`` TPU plugin and
pins ``JAX_PLATFORMS=axon`` before conftest runs, so setting the env
var here is not enough — the config flag must be overridden after the
jax import (backend selection happens lazily on first device use).
"""

import os

# never attempt dataset downloads from tests — zero-egress sandboxes
# can stall on connect timeouts; synthetic fallbacks are the contract
os.environ.setdefault("PERCEIVER_TPU_OFFLINE", "1")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
